"""Disaggregated prefill/decode engine (ISSUE 19): MPMD phase slices
with page-ownership handoff.

The colocated paged engine is the standing parity oracle — greedy
outputs must be BIT-IDENTICAL across the split for llama-GQA and qwen3
schedules, with the one-compile discipline on BOTH slice programs
(``prefill_compile_count == 1`` and ``decode_compile_count == 1``
through admissions, handoffs, quarantines and transport faults).
Conservation is the other oracle: both pools' ``check_conservation``
stay green under randomized admit/handoff/crash-mid-handoff/cancel/
drain schedules, and every request ends in exactly ONE of the six
terminal outcomes. Quick tier, CPU (8 virtual devices via conftest).
"""

import random
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scaletorch_tpu.inference import (
    DisaggregatedEngine,
    InferenceEngine,
    PageHandoffChannel,
    SamplingParams,
)
from scaletorch_tpu.inference.disagg import (
    parse_disagg_spec,
    plan_slice_split,
)
from scaletorch_tpu.models import llama, qwen3

TINY = dict(
    vocab_size=64, hidden_size=32, intermediate_size=64,
    num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
    dtype=jnp.float32,
)
GREEDY = SamplingParams(temperature=0.0)
SCHEDULE = [([1, 2, 3], 3), ([9, 8], 5), ([4, 5, 6, 7], 2), ([11], 6),
            ([1, 2, 3, 5], 4)]
OUTCOMES = {"ok", "timeout", "aborted", "quarantined", "rejected", "shed"}


@pytest.fixture(scope="module")
def tiny_llama():
    cfg = llama.LlamaConfig(**TINY)
    return cfg, llama.init_params(jax.random.PRNGKey(0), cfg)


def make_colocated(params, cfg, **kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("max_seq", 32)
    kw.setdefault("prefill_len", 8)
    kw.setdefault("sampling", GREEDY)
    kw.setdefault("cache_layout", "paged")
    kw.setdefault("page_size", 4)
    return InferenceEngine(params, cfg, **kw)


def make_disagg(params, cfg, **kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("max_seq", 32)
    kw.setdefault("prefill_len", 8)
    kw.setdefault("sampling", GREEDY)
    kw.setdefault("page_size", 4)
    kw.setdefault("disagg_split", (4, 4))
    return DisaggregatedEngine(params, cfg, **kw)


def serve(eng, schedule=SCHEDULE):
    ids = [eng.submit(p, max_new_tokens=n) for p, n in schedule]
    results = eng.run()
    return [results[i] for i in ids]


def poisoned(cfg):
    """Forward whose logits NaN whenever the magic token 63 appears —
    the poison-REQUEST drill from the resilience suite."""
    base = llama.forward_cached

    def forward(params, tokens, cfg, cache, *, positions,
                write_mask=None, **kw):
        logits, new_cache = base(params, tokens, cfg, cache,
                                 positions=positions,
                                 write_mask=write_mask, **kw)
        bad = jnp.any(tokens == 63, axis=-1)
        return jnp.where(bad[:, None, None], jnp.nan, logits), new_cache

    return forward


def assert_conserved_both(eng):
    """After a drain, NEITHER pool leaked: conservation green on both
    allocators, and evicting the decode-side radix returns BOTH pools
    to full capacity (the prefill pool holds nothing across ticks)."""
    eng.check_conservation()
    assert all(not s.active for s in eng._slots)
    assert not eng._handoff
    if eng.radix is not None:
        eng.radix.evict(eng.num_pages)
    assert eng.allocator.free_count == eng.allocator.capacity
    assert (eng.prefill_allocator.free_count
            == eng.prefill_allocator.capacity)


class TestDisaggParity:
    """Acceptance: disagg greedy outputs == colocated, both compile
    counts == 1, conservation green after drain."""

    def _check(self, cfg, params, **kw):
        colo = serve(make_colocated(params, cfg))
        eng = make_disagg(params, cfg, **kw)
        dis = serve(eng)
        for c, d in zip(colo, dis):
            assert d.tokens == c.tokens
            assert d.finish_reason == c.finish_reason
            assert d.outcome == "ok"
        assert eng.prefill_compile_count == 1
        assert eng.decode_compile_count == 1
        assert eng.metrics.handoffs > 0
        assert_conserved_both(eng)
        return eng

    def test_llama_gqa(self, tiny_llama):
        self._check(*tiny_llama)

    def test_qwen3(self):
        cfg = qwen3.Qwen3Config(**{**TINY, "head_dim": 16})
        self._check(cfg, qwen3.init_params(jax.random.PRNGKey(0), cfg))

    def test_prefix_cache_off_still_identical(self, tiny_llama):
        cfg, params = tiny_llama
        eng = self._check(cfg, params, prefix_cache=False)
        assert eng.radix is None

    def test_auto_split_follows_budget_plan(self, tiny_llama):
        """disagg_split=None sizes the slices from the CI-attested HBM
        budget rows — on the 8-virtual-device mesh that must equal
        plan_slice_split's answer, and parity must hold on it too."""
        cfg, params = tiny_llama
        n_p, n_d = plan_slice_split(len(jax.devices()))
        eng = self._check(cfg, params, disagg_split=None)
        assert eng.metrics.prefill_slice_devices == n_p
        assert eng.metrics.decode_slice_devices == n_d

    def test_quarantine_drill_matches_colocated(self, tiny_llama):
        """A poison prompt quarantines at the PREFILL slice (tokens [],
        prefill-pool lines cleared + released); its neighbour's output
        stays bit-identical to the colocated engine under the same
        drill, with zero retraces on either slice program."""
        cfg, params = tiny_llama
        schedule = [([1, 2, 63], 4), ([7, 8, 9], 4)]
        colo = serve(
            make_colocated(params, cfg, forward_fn=poisoned(cfg)),
            schedule)
        eng = make_disagg(params, cfg, forward_fn=poisoned(cfg))
        dis = serve(eng, schedule)
        for c, d in zip(colo, dis):
            assert d.outcome == c.outcome
            assert d.tokens == c.tokens
        assert dis[0].outcome == "quarantined"
        assert dis[0].tokens == []
        assert "prefill" in dis[0].detail
        assert dis[1].outcome == "ok"
        assert eng.prefill_compile_count == 1
        assert eng.decode_compile_count == 1
        assert_conserved_both(eng)


class TestHandoffProperties:
    def test_counters_and_channel_agree(self, tiny_llama):
        cfg, params = tiny_llama
        eng = make_disagg(params, cfg)
        serve(eng)
        m = eng.metrics
        assert m.handoffs == eng.channel.transfers
        assert m.pages_handed_off == eng.channel.pages_transferred > 0
        assert m.handoff_bytes == eng.channel.bytes_transferred > 0
        assert m.hist["handoff"].count == m.handoffs
        snap = m.snapshot()
        for key in ("prefill_slice_devices", "decode_slice_devices",
                    "handoffs", "pages_handed_off", "handoff_bytes",
                    "prefill_slice_busy_fraction",
                    "decode_slice_busy_fraction", "prefill_pool_free"):
            assert key in snap, key
        busy_p, busy_d = m.busy_fractions()
        assert 0.0 < busy_p <= 1.0
        assert 0.0 < busy_d <= 1.0

    def test_prefix_sharing_transfers_fewer_pages(self, tiny_llama):
        """The decode-side radix keeps handed-off prompt pages frozen:
        a second request with the same page-aligned prefix retains the
        shared pages on the decode pool and only the tail page crosses
        the wire — the hit saves TRANSFER, visible in the channel."""
        cfg, params = tiny_llama
        sys_prompt = [7, 7, 7, 7, 3, 3, 3, 3]  # two full pages
        eng = make_disagg(params, cfg, prefill_len=12)
        r1 = eng.submit(sys_prompt + [1], max_new_tokens=4)
        eng.run()
        first_pages = eng.channel.pages_transferred
        assert first_pages == 3  # ceil(9 / 4)
        r2 = eng.submit(sys_prompt + [2], max_new_tokens=4)
        results = eng.run()
        assert eng.channel.pages_transferred - first_pages == 1
        assert eng.metrics.prefix_hits == 1
        # disagg always prefills the full prompt — the hit must NOT
        # claim saved prefill tokens
        assert eng.metrics.prefill_tokens_saved == 0
        ref = make_colocated(params, cfg, prefill_len=12)
        rr = ref.submit(sys_prompt + [2], max_new_tokens=4)
        assert results[r2].tokens == ref.run()[rr].tokens
        assert results[r1].tokens is not None
        assert eng.decode_compile_count == 1
        assert_conserved_both(eng)

    def test_stop_at_first_token_skips_handoff(self, tiny_llama):
        """max_new_tokens=1 finishes at the prefill slice: one token,
        reason 'length', zero handoffs, prefill pages released."""
        cfg, params = tiny_llama
        eng = make_disagg(params, cfg)
        res = serve(eng, [([1, 2, 3], 1)])[0]
        assert res.outcome == "ok"
        assert res.finish_reason == "length"
        assert len(res.tokens) == 1
        ref = serve(make_colocated(params, cfg), [([1, 2, 3], 1)])[0]
        assert res.tokens == ref.tokens
        assert eng.metrics.handoffs == 0
        assert eng.channel.transfers == 0
        assert eng.decode_compile_count == 0  # decode slice never ran
        assert_conserved_both(eng)


class TestMidHandoffDeath:
    def test_transport_fault_aborts_exactly_once(self, tiny_llama):
        """An injected wire fault on the FIRST transfer: that request
        ends aborted (its streamed first token attached), the decode-
        side reservation rolls back whole, the NEXT request hands off
        normally with bit-identical tokens — one terminal, zero leaks,
        zero retraces."""
        cfg, params = tiny_llama
        channel = PageHandoffChannel()
        channel.fail_next()
        eng = make_disagg(params, cfg, channel=channel)
        schedule = [([1, 2, 3], 5), ([7, 8, 9], 5)]
        aborted, ok = serve(eng, schedule)
        assert aborted.outcome == "aborted"
        assert "handoff failed" in aborted.detail
        assert len(aborted.tokens) == 1  # the already-streamed token
        assert ok.outcome == "ok"
        ref = serve(make_colocated(params, cfg), [([7, 8, 9], 5)])[0]
        assert ok.tokens == ref.tokens
        assert eng.metrics.handoff_failures == 1
        assert channel.failures == 1
        assert eng.metrics.handoffs == 1
        assert eng.prefill_compile_count == 1
        assert eng.decode_compile_count == 1
        assert_conserved_both(eng)

    def test_deadline_expires_awaiting_handoff(self, tiny_llama):
        """A prefilled request whose deadline passes while it queues for
        a decode slot ends as exactly one `timeout` — prefill pages
        released, the occupant request unaffected."""
        cfg, params = tiny_llama
        eng = make_disagg(params, cfg, max_slots=1)
        occupant = eng.submit([1, 2, 3], max_new_tokens=20)
        eng.step()  # occupant prefilled + bound to the only decode slot
        blocked = eng.submit([4, 5, 6], max_new_tokens=5, ttl_s=0.15)
        eng.step()  # blocked prefills, waits in the handoff queue
        assert len(eng._handoff) == 1
        time.sleep(0.2)
        eng.step()  # deadline sweep drops it
        results = eng.run()
        assert results[blocked].outcome == "timeout"
        assert "handoff" in results[blocked].detail
        assert len(results[blocked].tokens) == 1
        assert results[occupant].outcome == "ok"
        assert eng.decode_compile_count == 1
        assert_conserved_both(eng)

    def test_cancel_in_handoff_queue(self, tiny_llama):
        cfg, params = tiny_llama
        eng = make_disagg(params, cfg, max_slots=1)
        occupant = eng.submit([1, 2, 3], max_new_tokens=20)
        eng.step()
        blocked = eng.submit([4, 5, 6], max_new_tokens=5)
        eng.step()
        assert len(eng._handoff) == 1
        assert eng.cancel(blocked) is True
        assert not eng._handoff
        results = eng.run()
        assert results[blocked].outcome == "aborted"
        assert results[occupant].outcome == "ok"
        assert_conserved_both(eng)

    def test_drain_finishes_handoff_queue(self, tiny_llama):
        """A prefilled request parked in the handoff queue is IN-FLIGHT
        (its first token already streamed): a graceful drain completes
        it through the decode slice, bit-identical — it is not part of
        the never-admitted backlog drain aborts."""
        cfg, params = tiny_llama
        eng = make_disagg(params, cfg, max_slots=1)
        eng.submit([1, 2, 3], max_new_tokens=20)
        eng.step()
        blocked = eng.submit([4, 5, 6], max_new_tokens=5)
        eng.step()
        assert len(eng._handoff) == 1
        results = eng.drain()
        assert results[blocked].outcome == "ok"
        ref = serve(make_colocated(params, cfg, max_slots=1),
                    [([4, 5, 6], 5)])[0]
        assert results[blocked].tokens == ref.tokens
        assert_conserved_both(eng)


class TestRandomizedConservation:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_schedule_conserves_both_pools(self, tiny_llama, seed):
        """Randomized interleavings of submit (incl. poison prompts and
        near-expired deadlines), cancel, injected wire faults, and ticks
        — then a full drain. Oracle: submitted == sum(outcomes), every
        outcome one of the six terminals, conservation green on BOTH
        pools, the radix evictable back to full capacity, and at most
        one compile per slice program through it all."""
        cfg, params = tiny_llama
        channel = PageHandoffChannel()
        eng = make_disagg(params, cfg, channel=channel,
                          forward_fn=poisoned(cfg), strict_submit=False)
        rng = random.Random(seed)
        ids = []
        for _ in range(40):
            op = rng.random()
            if op < 0.5:
                prompt = [rng.randint(1, 62)
                          for _ in range(rng.randint(1, 8))]
                if rng.random() < 0.15:
                    prompt[-1] = 63  # poison -> quarantined at prefill
                kw = {}
                if rng.random() < 0.15:
                    kw["ttl_s"] = 0.001  # -> timeout somewhere en route
                ids.append(eng.submit(
                    prompt, max_new_tokens=rng.randint(1, 6), **kw))
            elif op < 0.62 and ids:
                eng.cancel(rng.choice(ids))
            elif op < 0.72:
                channel.fail_next()  # next handoff dies mid-wire
            else:
                eng.step()
        results = eng.run()
        assert len(ids) == eng.metrics.requests_submitted
        assert all(i in results for i in ids)
        assert sum(eng.metrics.outcomes.values()) == len(ids)
        assert set(eng.metrics.outcomes) <= OUTCOMES
        assert eng.prefill_compile_count == 1
        assert eng.decode_compile_count <= 1
        assert_conserved_both(eng)


class TestPlanningAndValidation:
    def test_parse_disagg_spec(self):
        assert parse_disagg_spec("4:4") == (4, 4)
        assert parse_disagg_spec(" 3:5 ") == (3, 5)
        assert parse_disagg_spec("") is None
        assert parse_disagg_spec("auto") is None
        assert parse_disagg_spec("none") is None
        for bad in ("4", "1:2:3", "a:b", "4:"):
            with pytest.raises(ValueError, match="disagg spec"):
                parse_disagg_spec(bad)
        with pytest.raises(ValueError, match=">= 1 device"):
            parse_disagg_spec("0:4")

    def test_plan_slice_split_reads_budget(self, tmp_path):
        budget = tmp_path / "hbm.json"
        budget.write_text(
            '{"entries": {"disagg_prefill_slice": {"peak_mb": 3.0}, '
            '"disagg_decode_slice": {"peak_mb": 1.0}}}')
        assert plan_slice_split(8, budget_path=str(budget)) == (6, 2)
        # unreadable budget degrades to an even split, never an error
        assert plan_slice_split(
            8, budget_path=str(tmp_path / "missing.json")) == (4, 4)
        # each slice always keeps at least one device
        assert plan_slice_split(2, budget_path=str(budget)) == (1, 1)
        with pytest.raises(ValueError, match=">= 2 devices"):
            plan_slice_split(1)

    def test_checked_in_budget_covers_the_mesh(self):
        """The real tools/hbm_budget.json rows must plan a valid split
        for the CI mesh (the sizing recipe the docs name)."""
        n_p, n_d = plan_slice_split(len(jax.devices()))
        assert n_p >= 1 and n_d >= 1
        assert n_p + n_d == len(jax.devices())

    def test_constructor_validation(self, tiny_llama):
        cfg, params = tiny_llama
        with pytest.raises(ValueError, match="paged"):
            make_disagg(params, cfg, cache_layout="dense")
        with pytest.raises(ValueError, match="slice meshes"):
            make_disagg(params, cfg, mesh=object())
        with pytest.raises(ValueError, match="devices"):
            make_disagg(params, cfg, disagg_split=(8, 8))
        with pytest.raises(ValueError, match=">= 2 devices"):
            make_disagg(params, cfg, devices=[jax.devices()[0]],
                        disagg_split=None)
        with pytest.raises(ValueError, match="prefill_pool_pages"):
            make_disagg(params, cfg, prefill_pool_pages=1)

    def test_slice_placement_is_disjoint(self, tiny_llama):
        """MPMD, attested on devices: the decode pool lives ONLY on
        decode-slice devices, the prefill pool + param copy ONLY on
        prefill-slice devices."""
        cfg, params = tiny_llama
        eng = make_disagg(params, cfg)
        prefill_devs = set(eng.prefill_mesh.devices.flat)
        decode_devs = set(eng.decode_mesh.devices.flat)
        assert not (prefill_devs & decode_devs)
        assert set(eng.cache.k.sharding.device_set) == decode_devs
        assert set(eng.prefill_cache.k.sharding.device_set) \
            == prefill_devs
        leaf = jax.tree.leaves(eng._params_prefill)[0]
        assert set(leaf.sharding.device_set) == prefill_devs


class TestDisaggTelemetry:
    def test_jsonl_export_carries_disagg_kind(self, tiny_llama, tmp_path):
        from scaletorch_tpu.telemetry.export import (
            KNOWN_KINDS,
            TelemetryExporter,
            read_jsonl,
        )

        assert "disagg" in KNOWN_KINDS
        cfg, params = tiny_llama
        path = str(tmp_path / "events.jsonl")
        exporter = TelemetryExporter(path)
        eng = make_disagg(params, cfg, exporter=exporter)
        serve(eng, [([1, 2, 3], 4)])
        exporter.close()
        records = read_jsonl(path)
        kinds = {r["kind"] for r in records}
        assert {"engine_metrics", "disagg"} <= kinds
        dis = [r for r in records if r["kind"] == "disagg"][-1]
        assert dis["prefill_slice_devices"] == 4
        assert dis["decode_slice_devices"] == 4
        assert dis["handoffs"] >= 1
        assert dis["pages_handed_off"] >= 1
        assert dis["handoff_failures"] == 0
