"""Continuous-batching engine: correctness, no-retrace, TP-sharded cache.

Quick tier, CPU. The no-retrace test is the ISSUE 4 acceptance gate: the
decode step must compile exactly once across a multi-request
continuous-batching run (admissions into freed slots change data, never
shapes).
"""

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from scaletorch_tpu.inference import (
    InferenceEngine,
    SamplingParams,
)
from scaletorch_tpu.models import llama, qwen3_moe

TINY = dict(
    vocab_size=64, hidden_size=32, intermediate_size=64,
    num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
    dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def tiny_llama():
    cfg = llama.LlamaConfig(**TINY)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def ref_greedy(params, cfg, prompt, n):
    """Oracle: repeated full-sequence forward + argmax."""
    toks = list(prompt)
    for _ in range(n):
        logits = llama.forward(params, jnp.asarray([toks], jnp.int32), cfg)
        toks.append(int(jnp.argmax(logits[0, -1])))
    return toks[len(prompt):]


class TestEngineCorrectness:
    def test_greedy_matches_full_forward_oracle(self, tiny_llama):
        cfg, params = tiny_llama
        eng = InferenceEngine(params, cfg, max_slots=2, max_seq=32,
                              prefill_len=8,
                              sampling=SamplingParams(temperature=0.0))
        prompts = [[1, 2, 3], [7, 8, 9, 10]]
        ids = [eng.submit(p, max_new_tokens=6) for p in prompts]
        results = eng.run()
        for rid, prompt in zip(ids, prompts):
            assert results[rid].tokens == ref_greedy(params, cfg, prompt, 6)
            assert results[rid].finish_reason == "length"
            assert results[rid].ttft_s >= 0

    def test_eos_stops_early(self, tiny_llama):
        cfg, params = tiny_llama
        eng = InferenceEngine(params, cfg, max_slots=1, max_seq=32,
                              prefill_len=8,
                              sampling=SamplingParams(temperature=0.0))
        expected = ref_greedy(params, cfg, [1, 2, 3], 6)
        eos = expected[2]  # generation must stop at eos's FIRST occurrence
        rid = eng.submit([1, 2, 3], max_new_tokens=6, eos_id=eos)
        results = eng.run()
        assert results[rid].finish_reason == "eos"
        assert results[rid].tokens == expected[:expected.index(eos) + 1]

    def test_max_seq_caps_generation(self, tiny_llama):
        cfg, params = tiny_llama
        eng = InferenceEngine(params, cfg, max_slots=1, max_seq=8,
                              prefill_len=4,
                              sampling=SamplingParams(temperature=0.0))
        rid = eng.submit([1, 2, 3], max_new_tokens=100)
        results = eng.run()
        assert results[rid].finish_reason == "max_seq"
        assert len(results[rid].tokens) + 3 <= 8

    def test_sampled_run_is_seed_deterministic(self, tiny_llama):
        cfg, params = tiny_llama

        def run_once():
            eng = InferenceEngine(
                params, cfg, max_slots=2, max_seq=24, prefill_len=8,
                sampling=SamplingParams(temperature=1.0, top_k=8),
            )
            rid = eng.submit([5, 6], max_new_tokens=5, seed=123)
            return eng.run()[rid].tokens

        assert run_once() == run_once()

    def test_submit_validation(self, tiny_llama):
        cfg, params = tiny_llama
        eng = InferenceEngine(params, cfg, max_slots=1, max_seq=4,
                              prefill_len=4)
        with pytest.raises(ValueError, match="at least one token"):
            eng.submit([])
        with pytest.raises(ValueError, match="prefill buffer"):
            eng.submit([1] * 5)
        with pytest.raises(ValueError, match="no room"):
            # fits the prefill buffer but fills max_seq completely
            eng.submit([1] * 4, max_new_tokens=1)


class TestContinuousBatching:
    def test_no_retrace_across_admissions(self, tiny_llama):
        """More requests than slots: later requests are admitted into
        freed slots mid-run; the decode step must have compiled exactly
        once by the end — the jitted step never retraces."""
        cfg, params = tiny_llama
        eng = InferenceEngine(params, cfg, max_slots=2, max_seq=32,
                              prefill_len=8,
                              sampling=SamplingParams(temperature=0.0))
        prompts = [[1, 2, 3], [9, 8], [4, 5, 6, 7], [11], [20, 21]]
        lens = [3, 5, 2, 6, 4]
        ids = [eng.submit(p, max_new_tokens=n)
               for p, n in zip(prompts, lens)]
        results = eng.run()
        assert eng.decode_compile_count == 1
        assert eng.prefill_compile_count == 1
        assert eng.metrics.prefill_calls >= 2  # admissions happened mid-run
        for rid, prompt, n in zip(ids, prompts, lens):
            assert results[rid].tokens == ref_greedy(params, cfg, prompt, n)

    def test_slot_reuse_does_not_leak_state(self, tiny_llama):
        """A request admitted into a reused slot sees none of the
        previous occupant's cache: its output equals a fresh engine's."""
        cfg, params = tiny_llama
        eng = InferenceEngine(params, cfg, max_slots=1, max_seq=32,
                              prefill_len=8,
                              sampling=SamplingParams(temperature=0.0))
        eng.submit([1, 2, 3], max_new_tokens=4)
        second = eng.submit([9, 8, 7], max_new_tokens=4)
        results = eng.run()
        assert results[second].tokens == ref_greedy(params, cfg, [9, 8, 7], 4)

    def test_metrics_accounting(self, tiny_llama):
        cfg, params = tiny_llama
        eng = InferenceEngine(params, cfg, max_slots=2, max_seq=24,
                              prefill_len=8,
                              sampling=SamplingParams(temperature=0.0))
        eng.submit([1, 2], max_new_tokens=3)
        eng.submit([3, 4], max_new_tokens=5)
        eng.run()
        snap = eng.metrics.snapshot()
        assert snap["requests_completed"] == 2
        assert snap["tokens_generated"] == 8
        assert snap["mean_ttft_s"] > 0
        assert snap["queue_depth"] == 0

    def test_metrics_ride_monitor_ring_buffer(self, tiny_llama):
        psutil = pytest.importorskip("psutil")  # noqa: F841
        from scaletorch_tpu.utils.monitor import SystemMonitor

        cfg, params = tiny_llama
        mon = SystemMonitor(max_records=16)
        eng = InferenceEngine(params, cfg, max_slots=1, max_seq=24,
                              prefill_len=8, monitor=mon, monitor_every=1,
                              sampling=SamplingParams(temperature=0.0))
        eng.submit([1, 2], max_new_tokens=4)
        eng.run()
        assert mon.records
        assert "tokens_generated" in mon.records[-1]


class TestTokenHookAndCancel:
    """ISSUE 11 satellites: the streaming bridge's engine surface —
    per-tick ``on_tokens`` push, ``tick()`` driving, ``cancel()``."""

    def test_on_tokens_concatenates_to_final_result_bit_exactly(
            self, tiny_llama):
        cfg, params = tiny_llama
        streamed = {}

        def hook(slot, request_id, token_ids):
            streamed.setdefault(request_id, []).extend(token_ids)

        eng = InferenceEngine(params, cfg, max_slots=2, max_seq=32,
                              prefill_len=8,
                              sampling=SamplingParams(temperature=0.0),
                              on_tokens=hook)
        prompts = [[1, 2, 3], [9, 8], [4, 5, 6, 7], [11]]
        ids = [eng.submit(p, max_new_tokens=n)
               for p, n in zip(prompts, [6, 3, 5, 4])]
        results = eng.run()
        for rid in ids:
            assert streamed[rid] == results[rid].tokens  # bit-exact
        assert eng.decode_compile_count == 1  # the hook adds no retrace

    def test_on_tokens_pushed_per_tick_not_at_terminal(self, tiny_llama):
        """The hook must fire DURING generation (push), not once at the
        end: drive tick-by-tick and watch tokens arrive incrementally."""
        cfg, params = tiny_llama
        seen = []
        eng = InferenceEngine(
            params, cfg, max_slots=1, max_seq=32, prefill_len=8,
            sampling=SamplingParams(temperature=0.0),
            on_tokens=lambda s, r, t: seen.extend(t))
        eng.submit([1, 2, 3], max_new_tokens=5)
        counts = []
        while eng.pending:
            eng.tick()
            counts.append(len(seen))
        assert len(seen) == 5
        assert counts == sorted(counts) and len(set(counts)) > 2

    def test_raising_hook_is_disarmed_not_fatal(self, tiny_llama):
        cfg, params = tiny_llama

        def bad_hook(slot, request_id, token_ids):
            raise RuntimeError("consumer bug")

        eng = InferenceEngine(params, cfg, max_slots=1, max_seq=32,
                              prefill_len=8,
                              sampling=SamplingParams(temperature=0.0),
                              on_tokens=bad_hook)
        rid = eng.submit([1, 2, 3], max_new_tokens=4)
        results = eng.run()
        assert results[rid].outcome == "ok"
        assert eng.on_tokens is None  # disarmed after the first raise

    def test_cancel_queued_and_mid_decode(self, tiny_llama):
        cfg, params = tiny_llama
        eng = InferenceEngine(params, cfg, max_slots=1, max_seq=32,
                              prefill_len=8,
                              sampling=SamplingParams(temperature=0.0))
        active = eng.submit([1, 2, 3], max_new_tokens=10)
        queued = eng.submit([4, 5], max_new_tokens=10)
        eng.step()                      # admit + first decode of `active`
        assert eng.cancel(queued, detail="client gone")
        finished = eng.step()           # the cancel is delivered this tick
        assert any(r.request_id == queued and r.outcome == "aborted"
                   for r in finished)
        assert eng.cancel(active)
        assert eng.result(active).outcome == "aborted"
        assert eng.result(active).tokens  # partials attached
        assert not eng.cancel(active)   # already terminal
        assert not eng.cancel(12345)    # unknown id
        # conservation holds across cancels
        assert sum(eng.metrics.outcomes.values()) == 2

    def test_cancel_releases_pages(self, tiny_llama):
        cfg, params = tiny_llama
        eng = InferenceEngine(params, cfg, max_slots=1, max_seq=32,
                              prefill_len=8, cache_layout="paged",
                              page_size=4,
                              sampling=SamplingParams(temperature=0.0))
        rid = eng.submit([1, 2, 3, 4, 5], max_new_tokens=20)
        eng.step()
        assert eng.metrics.pages_in_use > 0
        assert eng.cancel(rid)
        eng.allocator.check_conservation()
        # only the radix tree's own references may remain
        assert all(c == 1 for c in eng.allocator._ref.values())

    def test_stop_admissions_without_tick_loop(self, tiny_llama):
        """The bridge-owned drain: stop_admissions() blocks submits but
        the owner keeps ticking in-flight work to completion."""
        cfg, params = tiny_llama
        eng = InferenceEngine(params, cfg, max_slots=1, max_seq=32,
                              prefill_len=8, strict_submit=False,
                              sampling=SamplingParams(temperature=0.0))
        rid = eng.submit([1, 2, 3], max_new_tokens=4)
        eng.stop_admissions()
        late = eng.submit([7], max_new_tokens=2)
        assert eng.result(late).outcome == "rejected"
        while eng.pending:
            eng.tick()
        assert eng.result(rid).outcome == "ok"
        assert len(eng.result(rid).tokens) == 4


class TestShardedServing:
    def test_tp_sharded_cache_matches_unsharded(self, tiny_llama, mm_factory):
        """ISSUE 4 acceptance: the TP-sharded cache path runs green on
        the 8-device virtual mesh — params per llama_param_specs, cache
        KV-heads over tp, GSPMD decode — and reproduces the unsharded
        engine's greedy output."""
        from scaletorch_tpu.parallel.tensor_parallel import llama_param_specs

        cfg, params = tiny_llama
        e0 = InferenceEngine(params, cfg, max_slots=2, max_seq=24,
                             prefill_len=8,
                             sampling=SamplingParams(temperature=0.0))
        r0 = e0.submit([1, 2, 3], max_new_tokens=6)
        expected = e0.run()[r0].tokens

        mm = mm_factory(tp=2, dp=4)
        specs = llama_param_specs(cfg, tp_axis="tp")
        shardings = jax.tree.map(
            lambda s: NamedSharding(mm.mesh, s), specs,
            is_leaf=lambda x: isinstance(x, P),
        )
        params_sh = jax.tree.map(jax.device_put, params, shardings)
        eng = InferenceEngine(params_sh, cfg, max_slots=2, max_seq=24,
                              prefill_len=8, mesh=mm.mesh, tp_axis="tp",
                              sampling=SamplingParams(temperature=0.0))
        assert eng.cache.k.sharding.spec[2] == "tp"
        rid = eng.submit([1, 2, 3], max_new_tokens=6)
        results = eng.run()
        assert results[rid].tokens == expected
        assert eng.decode_compile_count == 1

    def test_qwen3_moe_engine_runs(self):
        """MoE decode through the engine (per-token routing, capacity 1)."""
        cfg = qwen3_moe.Qwen3MoEConfig(
            **{**TINY, "head_dim": 16}, moe_intermediate_size=48,
            num_experts=4, num_experts_per_tok=2, capacity_factor=2.0,
            tie_word_embeddings=False,
        )
        params = qwen3_moe.init_params(jax.random.PRNGKey(0), cfg)
        eng = InferenceEngine(params, cfg, max_slots=2, max_seq=24,
                              prefill_len=8,
                              sampling=SamplingParams(temperature=0.0))
        rid = eng.submit([1, 2, 3], max_new_tokens=5)
        results = eng.run()
        assert len(results[rid].tokens) == 5
        # oracle: repeated full forward
        toks = [1, 2, 3]
        for _ in range(5):
            logits = qwen3_moe.forward(
                params, jnp.asarray([toks], jnp.int32), cfg)
            toks.append(int(jnp.argmax(logits[0, -1])))
        assert results[rid].tokens == toks[3:]


class TestRequestScopedObservability:
    """ISSUE 12: trace_id threads through submit into lifecycle spans,
    the terminal result carries latency attribution, and the engine's
    latency histograms fill — all host-side, with greedy outputs and
    the one-compile discipline untouched."""

    TRACE = "0af7651916cd43dd8448eb211c80319c"

    def test_result_latency_attribution_and_histograms(self, tiny_llama):
        cfg, params = tiny_llama
        eng = InferenceEngine(params, cfg, max_slots=2, max_seq=32,
                              prefill_len=8,
                              sampling=SamplingParams(temperature=0.0))
        rid = eng.submit([1, 2, 3], max_new_tokens=6)
        result = eng.run()[rid]
        assert result.queue_wait_s is not None and result.queue_wait_s >= 0
        assert result.prefill_s is not None and result.prefill_s > 0
        assert result.prefix_hit is False
        assert result.trace_id is None  # untraced submit stays untraced
        hist = eng.metrics.hist
        assert hist["ttft"].count == 1
        assert hist["queue_wait"].count == 1
        assert hist["prefill"].count == 1
        assert hist["e2e"].count == 1
        assert hist["tpot"].count == 5  # 6 tokens -> 5 inter-arrivals
        state = eng.metrics.histogram_state()
        assert state["e2e"]["count"] == 1
        # distribution sanity: e2e covers ttft
        assert hist["e2e"].quantile(0.5) >= hist["ttft"].min

    def test_trace_id_spans_and_bit_identical_outputs(self, tiny_llama):
        from scaletorch_tpu.telemetry.spans import SpanTracer

        cfg, params = tiny_llama

        def run(tracer, trace_id):
            eng = InferenceEngine(params, cfg, max_slots=2, max_seq=32,
                                  prefill_len=8, tracer=tracer,
                                  sampling=SamplingParams(temperature=0.0))
            rid = eng.submit([1, 2, 3], max_new_tokens=6,
                             trace_id=trace_id)
            result = eng.run()[rid]
            assert eng.decode_compile_count == 1
            return result

        plain = run(None, None)
        tracer = SpanTracer(path=None, role="serve")  # memory-only
        traced = run(tracer, self.TRACE)
        # instrumentation changes NOTHING functional
        assert traced.tokens == plain.tokens
        assert traced.trace_id == self.TRACE
        ours = [e for e in tracer.tail() if e.get("id") == self.TRACE]
        names = [e["name"] for e in ours]
        for name in ("request", "req.queued", "req.admitted",
                     "req.prefill", "req.decode", "req.finalize"):
            assert name in names, (name, names)
        # balanced async begin/end per span name
        for name in ("request", "req.queued", "req.prefill", "req.decode"):
            phases = [e["ph"] for e in ours if e["name"] == name]
            assert phases == ["b", "e"], (name, phases)
        finalize = [e for e in ours if e["name"] == "req.finalize"][0]
        assert finalize["args"]["outcome"] == "ok"

    def test_rejected_and_cancelled_spans_balance(self, tiny_llama):
        from scaletorch_tpu.telemetry.spans import SpanTracer

        cfg, params = tiny_llama
        tracer = SpanTracer(path=None, role="serve")
        eng = InferenceEngine(params, cfg, max_slots=1, max_seq=16,
                              prefill_len=8, tracer=tracer,
                              strict_submit=False,
                              sampling=SamplingParams(temperature=0.0))
        # rejected at submit: request + queued both close immediately
        bad = eng.submit([], trace_id="11" * 16)
        assert eng.result(bad).outcome == "rejected"
        # cancelled while queued: queued span closes, never decode
        rid = eng.submit([1, 2], max_new_tokens=4, trace_id="22" * 16)
        assert eng.cancel(rid)
        for trace_id in ("11" * 16, "22" * 16):
            ours = [e for e in tracer.tail() if e.get("id") == trace_id]
            for name in ("request", "req.queued"):
                phases = [e["ph"] for e in ours if e["name"] == name]
                assert phases == ["b", "e"], (trace_id, name, phases)
            assert not any(e["name"] == "req.decode" for e in ours)

    def test_unserved_outcomes_stay_out_of_e2e_histogram(self, tiny_llama):
        """Instant rejects and client-cancelled (aborted) slots must
        not feed the e2e tail estimate — only served (ok/timeout)
        requests do."""
        cfg, params = tiny_llama
        eng = InferenceEngine(params, cfg, max_slots=1, max_seq=32,
                              prefill_len=8, strict_submit=False,
                              sampling=SamplingParams(temperature=0.0))
        eng.submit([])  # rejected at submit
        rid = eng.submit([1, 2], max_new_tokens=20)
        eng.step()      # admitted, first token
        assert eng.cancel(rid)  # aborted mid-decode, admit_time set
        ok = eng.submit([1, 2, 3], max_new_tokens=2)
        eng.run()
        assert eng.result(ok).outcome == "ok"
        assert eng.metrics.hist["e2e"].count == 1  # the ok request only
