"""Serving resilience: outcome taxonomy, shedding, quarantine, drain.

Quick tier, CPU. The hermetic end-to-end drills of ISSUE 8: every
submitted request ends in exactly one terminal outcome (the conservation
invariant), injected NaN logits quarantine ONLY the poisoned slot while
the other slots' greedy outputs stay bit-identical to a fault-free run,
deadline/submit storms shed with the correct timeout/shed outcomes,
drain() under SIGTERM finishes in-flight requests, a stalled step fires
the serving watchdog (exit code 44) — and ``decode_compile_count == 1``
holds through all of it.
"""

import json
import os
import random
import signal
from collections import Counter

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scaletorch_tpu.inference import (
    SERVING_STALL_EXIT_CODE,
    TERMINAL_OUTCOMES,
    EngineDraining,
    InferenceEngine,
    SamplingParams,
    ServingFaultInjector,
    make_prefill_step,
    make_serving_watchdog,
)
from scaletorch_tpu.models import llama
from scaletorch_tpu.resilience import PreemptionHandler

TINY = dict(
    vocab_size=64, hidden_size=32, intermediate_size=64,
    num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
    dtype=jnp.float32,
)
GREEDY = SamplingParams(temperature=0.0)


@pytest.fixture(scope="module")
def tiny_llama():
    cfg = llama.LlamaConfig(**TINY)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def make_engine(tiny_llama, **kw):
    cfg, params = tiny_llama
    kw.setdefault("max_slots", 2)
    kw.setdefault("max_seq", 32)
    kw.setdefault("prefill_len", 8)
    kw.setdefault("sampling", GREEDY)
    return InferenceEngine(params, cfg, **kw)


def ref_greedy(params, cfg, prompt, n):
    """Oracle: repeated full-sequence forward + argmax."""
    toks = list(prompt)
    for _ in range(n):
        logits = llama.forward(params, jnp.asarray([toks], jnp.int32), cfg)
        toks.append(int(jnp.argmax(logits[0, -1])))
    return toks[len(prompt):]


def assert_conserved(eng):
    """The ISSUE 8 conservation invariant: every submitted request has
    exactly one terminal result, no slot stays active past its request's
    terminal outcome, and the compiled steps never retraced."""
    m = eng.metrics
    assert m.requests_submitted == sum(m.outcomes.values())
    assert m.requests_submitted == len(eng._results)
    assert all(r.outcome in TERMINAL_OUTCOMES for r in eng._results.values())
    assert not any(s.active for s in eng._slots)
    assert eng.pending == 0
    assert eng.decode_compile_count <= 1
    assert eng.prefill_compile_count <= 1


class TestOutcomeTaxonomy:
    def test_run_exhaustion_returns_partials_as_aborted(self, tiny_llama):
        """Satellite: run(max_steps) must return the completed work and
        mark the unfinished requests aborted — not raise away finished
        results."""
        eng = make_engine(tiny_llama, max_slots=1)
        done = eng.submit([1, 2, 3], max_new_tokens=2)
        hung = eng.submit([7, 8], max_new_tokens=25)   # needs ~25 steps
        results = eng.run(max_steps=6)
        cfg, params = tiny_llama
        assert results[done].outcome == "ok"
        assert results[done].tokens == ref_greedy(params, cfg, [1, 2, 3], 2)
        assert results[hung].outcome == "aborted"
        assert results[hung].finish_reason == "aborted"
        assert len(results[hung].tokens) > 0     # partials attached
        assert "exhausted" in results[hung].detail
        assert_conserved(eng)

    def test_strict_submit_still_raises(self, tiny_llama):
        """Backward compatibility: the default engine raises on invalid
        prompts exactly as before."""
        eng = make_engine(tiny_llama, max_slots=1, max_seq=4, prefill_len=4)
        with pytest.raises(ValueError, match="at least one token"):
            eng.submit([])
        with pytest.raises(ValueError, match="prefill buffer"):
            eng.submit([1] * 5)
        with pytest.raises(ValueError, match="no room"):
            eng.submit([1] * 4, max_new_tokens=1)
        assert eng.metrics.requests_submitted == 0  # raises never count

    def test_nonstrict_submit_rejects_structurally(self, tiny_llama):
        """Satellite: strict_submit=False turns validation failures into
        `rejected` terminal results so a server loop survives them."""
        eng = make_engine(tiny_llama, max_slots=1, max_seq=4, prefill_len=4,
                          strict_submit=False)
        bad = [eng.submit([]), eng.submit([1] * 5),
               eng.submit([1] * 4, max_new_tokens=1)]
        good = eng.submit([1, 2], max_new_tokens=1)
        results = eng.run()
        for rid in bad:
            assert results[rid].outcome == "rejected"
            assert results[rid].tokens == []
            assert results[rid].detail
        assert results[good].outcome == "ok"
        assert_conserved(eng)

    def test_pop_result_reclaims_terminal_record(self, tiny_llama):
        """A long-running serving loop pops each delivered result so the
        record map cannot grow for the server's lifetime."""
        eng = make_engine(tiny_llama, max_slots=1)
        rid = eng.submit([1, 2], max_new_tokens=2)
        assert eng.pop_result(rid) is None       # not yet terminal
        eng.run()
        popped = eng.pop_result(rid)
        assert popped is not None and popped.outcome == "ok"
        assert eng.result(rid) is None           # reclaimed
        assert eng.pop_result(rid) is None       # idempotent
        # metrics still conserve: pop only drops the record, not the count
        assert eng.metrics.requests_submitted == sum(
            eng.metrics.outcomes.values())

    def test_queue_capacity_sheds_oldest_first(self, tiny_llama):
        eng = make_engine(tiny_llama, max_slots=1, queue_capacity=2)
        ids = [eng.submit([1, 2], max_new_tokens=2) for _ in range(5)]
        results = eng.run()
        outcomes = [results[r].outcome for r in ids]
        # oldest queued requests shed; the freshest survive
        assert outcomes.count("shed") == 3
        assert outcomes[-1] == "ok" and outcomes[-2] == "ok"
        assert outcomes[:3] == ["shed"] * 3
        assert results[ids[0]].latency_s is not None
        assert_conserved(eng)

    def test_queued_deadline_times_out_before_admission(self, tiny_llama):
        eng = make_engine(tiny_llama, max_slots=1)
        stale = eng.submit([1, 2], max_new_tokens=2, ttl_s=1e-9)
        fresh = eng.submit([1, 2, 3], max_new_tokens=2)  # no deadline
        results = eng.run()
        assert results[stale].outcome == "timeout"
        assert results[stale].tokens == []
        assert "before admission" in results[stale].detail
        assert results[fresh].outcome == "ok"
        assert_conserved(eng)

    def test_default_ttl_applies_when_submit_omits_it(self, tiny_llama):
        eng = make_engine(tiny_llama, default_ttl_s=1e-9)
        rid = eng.submit([1, 2], max_new_tokens=2)
        override = eng.submit([1, 2, 3], max_new_tokens=2, ttl_s=0)  # opt out
        results = eng.run()
        assert results[rid].outcome == "timeout"
        assert results[override].outcome == "ok"
        assert_conserved(eng)


class TestQuarantine:
    def test_nan_quarantines_only_poisoned_slot(self, tiny_llama):
        """ISSUE 8 acceptance: injected NaN logits quarantine the
        poisoned slot; the OTHER slot's greedy output stays bit-identical
        to a fault-free run; decode compiled exactly once throughout."""
        cfg, params = tiny_llama

        def run_engine(injector):
            eng = make_engine(tiny_llama, injector=injector)
            a = eng.submit([1, 2, 3], max_new_tokens=8)
            b = eng.submit([7, 8, 9, 10], max_new_tokens=8)
            return eng, a, b, eng.run()

        _, a0, b0, clean = run_engine(None)
        inj = ServingFaultInjector(nan_logits_at_step=3, nan_logits_slot=0)
        eng, a1, b1, faulty = run_engine(inj)

        assert clean[a0].outcome == clean[b0].outcome == "ok"
        assert faulty[a1].outcome == "quarantined"
        assert faulty[a1].finish_reason == "quarantined"
        # prefill token + 2 decode tokens landed before the poisoned step
        assert faulty[a1].tokens == clean[a0].tokens[:3]
        assert "non-finite" in faulty[a1].detail
        # the neighbour slot never noticed
        assert faulty[b1].outcome == "ok"
        assert faulty[b1].tokens == clean[b0].tokens
        assert eng.decode_compile_count == 1
        assert eng.prefill_compile_count == 1
        assert_conserved(eng)

    def test_slot_reuse_after_quarantine_is_clean(self, tiny_llama):
        """The quarantined slot's cache lines are mask-cleared: the next
        occupant's output equals a fresh engine's, and the decode step
        still never retraced."""
        cfg, params = tiny_llama
        inj = ServingFaultInjector(nan_logits_at_step=2, nan_logits_slot=0)
        eng = make_engine(tiny_llama, max_slots=1, injector=inj)
        poisoned = eng.submit([1, 2, 3], max_new_tokens=8)
        reused = eng.submit([9, 8, 7], max_new_tokens=4)
        results = eng.run()
        assert results[poisoned].outcome == "quarantined"
        assert results[reused].outcome == "ok"
        assert results[reused].tokens == ref_greedy(params, cfg, [9, 8, 7], 4)
        assert eng.decode_compile_count == 1
        assert_conserved(eng)

    def test_prefill_nonfinite_flag(self, tiny_llama):
        """Unit check of the in-step guard at prefill: a forward that
        NaNs one slot's logits flips exactly that slot's finite bit."""
        cfg, params = tiny_llama
        base = llama.forward_cached

        def poisoned_forward(params, tokens, cfg, cache, *, positions,
                             write_mask=None):
            logits, new_cache = base(params, tokens, cfg, cache,
                                     positions=positions,
                                     write_mask=write_mask)
            bad = jnp.any(tokens == 63, axis=-1)  # magic poison token
            logits = jnp.where(bad[:, None, None], jnp.nan, logits)
            return logits, new_cache

        prefill = make_prefill_step(cfg, GREEDY, forward_fn=poisoned_forward)
        from scaletorch_tpu.inference.kv_cache import init_kv_cache
        cache = init_kv_cache(cfg, 2, 16, dtype=jnp.float32)
        tokens = np.zeros((2, 8), np.int32)
        tokens[0, :3] = [1, 2, 63]      # poisoned prompt
        tokens[1, :3] = [1, 2, 3]
        _, _, finite, _ = prefill(
            params, jnp.asarray(tokens), jnp.asarray([3, 3], jnp.int32),
            jnp.asarray([True, True]), cache,
            jnp.zeros((2, 2), jnp.uint32),
        )
        assert list(np.asarray(finite)) == [False, True]

    def test_poison_request_quarantined_at_admission(self, tiny_llama):
        """End-to-end poison REQUEST: a prompt whose content NaNs the
        model is quarantined at admission (prefill), other requests are
        served normally."""
        cfg, params = tiny_llama
        base = llama.forward_cached

        def poisoned_forward(params, tokens, cfg, cache, *, positions,
                             write_mask=None):
            logits, new_cache = base(params, tokens, cfg, cache,
                                     positions=positions,
                                     write_mask=write_mask)
            bad = jnp.any(tokens == 63, axis=-1)
            logits = jnp.where(bad[:, None, None], jnp.nan, logits)
            return logits, new_cache

        eng = make_engine(tiny_llama, forward_fn=poisoned_forward)
        poison = eng.submit([1, 2, 63], max_new_tokens=4)
        normal = eng.submit([7, 8, 9], max_new_tokens=4)
        results = eng.run()
        assert results[poison].outcome == "quarantined"
        assert results[poison].tokens == []
        assert "prefill" in results[poison].detail
        assert results[normal].outcome == "ok"
        assert results[normal].tokens == ref_greedy(params, cfg, [7, 8, 9], 4)
        assert_conserved(eng)


class TestStorms:
    def test_submit_storm_sheds(self, tiny_llama):
        """A burst beyond queue capacity sheds oldest-first with `shed`
        outcomes; the engine keeps serving."""
        inj = ServingFaultInjector(submit_storm_at_step=2,
                                   submit_storm_count=6)
        eng = make_engine(tiny_llama, max_slots=1, queue_capacity=2,
                          injector=inj)
        rid = eng.submit([1, 2, 3], max_new_tokens=6)
        results = eng.run()
        counts = Counter(r.outcome for r in results.values())
        assert results[rid].outcome == "ok"
        assert counts["shed"] == 4          # 6 injected, capacity 2 kept
        assert counts["ok"] == 1 + 2        # original + the 2 kept storms
        assert eng.metrics.requests_submitted == 7
        assert_conserved(eng)

    def test_deadline_storm_times_out_in_flight(self, tiny_llama):
        """A deadline storm expires queued AND mid-decode requests with
        `timeout` outcomes; partial tokens are kept; the engine survives
        and the metrics expose the deadline-miss rate."""
        inj = ServingFaultInjector(deadline_storm_at_step=3)
        eng = make_engine(tiny_llama, max_slots=1, injector=inj)
        active = eng.submit([1, 2, 3], max_new_tokens=20)
        queued = eng.submit([7, 8], max_new_tokens=2)
        results = eng.run()
        assert results[active].outcome == "timeout"
        assert "mid-decode" in results[active].detail
        assert len(results[active].tokens) == 3  # prefill + 2 decode steps
        assert results[queued].outcome == "timeout"
        assert "before admission" in results[queued].detail
        snap = eng.metrics.snapshot()
        assert snap["deadline_miss_rate"] == 1.0
        assert_conserved(eng)

    def test_post_storm_requests_serve_normally(self, tiny_llama):
        """After a deadline storm the engine must self-heal: later
        requests complete ok."""
        cfg, params = tiny_llama
        inj = ServingFaultInjector(deadline_storm_at_step=1)
        eng = make_engine(tiny_llama, max_slots=1, injector=inj)
        eng.submit([1, 2, 3], max_new_tokens=10)
        eng.run()
        rid = eng.submit([4, 5, 6], max_new_tokens=4)
        results = eng.run()
        assert results[rid].outcome == "ok"
        assert results[rid].tokens == ref_greedy(params, cfg, [4, 5, 6], 4)
        assert eng.decode_compile_count == 1
        assert_conserved(eng)


class TestDrain:
    def test_drain_finishes_in_flight_and_stops_admissions(self, tiny_llama):
        eng = make_engine(tiny_llama, max_slots=1)
        admitted = eng.submit([1, 2, 3], max_new_tokens=4)
        queued = eng.submit([7, 8], max_new_tokens=2)
        eng.step()                        # admit the first request
        results = eng.drain()
        assert results[admitted].outcome == "ok"
        assert len(results[admitted].tokens) == 4
        assert results[queued].outcome == "aborted"   # never admitted
        assert eng.draining
        with pytest.raises(EngineDraining):
            eng.submit([1, 2], max_new_tokens=1)
        assert_conserved(eng)

    def test_drain_finish_queued_serves_everything(self, tiny_llama):
        eng = make_engine(tiny_llama, max_slots=1)
        ids = [eng.submit([1, 2], max_new_tokens=2) for _ in range(3)]
        results = eng.drain(finish_queued=True)
        assert all(results[r].outcome == "ok" for r in ids)
        assert_conserved(eng)

    def test_drain_nonstrict_submit_rejects(self, tiny_llama):
        eng = make_engine(tiny_llama, strict_submit=False)
        eng.drain()
        rid = eng.submit([1, 2], max_new_tokens=1)
        res = eng.result(rid)
        assert res.outcome == "rejected"
        assert "draining" in res.detail
        assert_conserved(eng)

    def test_sigterm_drains_and_returns_cleanly(self, tiny_llama):
        """ISSUE 8 acceptance: drain() under SIGTERM finishes in-flight
        requests and run() returns cleanly — the existing
        PreemptionHandler SIGTERM path, not a new signal stack."""
        handler = PreemptionHandler()
        eng = make_engine(tiny_llama, max_slots=1, preemption=handler)
        admitted = eng.submit([1, 2, 3], max_new_tokens=6)
        queued = eng.submit([7, 8], max_new_tokens=30)
        eng.step()                                   # admit request 0
        handler.trigger(signal.SIGTERM)              # simulated delivery
        results = eng.run()
        assert results[admitted].outcome == "ok"
        assert len(results[admitted].tokens) == 6    # finished, not cut
        assert results[queued].outcome == "aborted"
        assert eng.draining
        assert eng.decode_compile_count == 1
        assert_conserved(eng)


class TestWatchdog:
    def test_slow_decode_fires_serving_watchdog(self, tiny_llama, tmp_path):
        """A stalled step() fires the serving watchdog: crash report with
        the engine metrics snapshot (outcome counters included) and exit
        code 44 — with an injected exit_fn recorder standing in for
        os._exit."""
        exits = []
        inj = ServingFaultInjector(slow_decode_at_step=2,
                                   slow_decode_seconds=0.6)
        eng = make_engine(tiny_llama, max_slots=1, injector=inj)
        wd = make_serving_watchdog(
            eng, timeout=0.15, crash_report_dir=str(tmp_path),
            exit_fn=exits.append)
        assert eng.watchdog is wd
        rid = eng.submit([1, 2, 3], max_new_tokens=4)
        with wd:
            results = eng.run()
        assert exits == [SERVING_STALL_EXIT_CODE]
        assert wd.fired
        # the injected exit_fn does not kill the process, so the stalled
        # step completes and the request still lands
        assert results[rid].outcome == "ok"
        reports = [f for f in os.listdir(tmp_path)
                   if f.startswith("crash_report")]
        assert len(reports) == 1
        with open(tmp_path / reports[0]) as f:
            report = json.load(f)
        assert report["serving"] is True
        assert report["exit_code"] == SERVING_STALL_EXIT_CODE
        assert "requests_quarantined" in report["counters"]
        assert "thread_stacks" in report

    def test_healthy_run_never_fires(self, tiny_llama):
        exits = []
        eng = make_engine(tiny_llama, max_slots=1)
        wd = make_serving_watchdog(eng, timeout=30.0, exit_fn=exits.append)
        rid = eng.submit([1, 2], max_new_tokens=2)
        with wd:
            results = eng.run()
        assert not wd.fired and exits == []
        assert results[rid].outcome == "ok"


class TestConservationProperty:
    @pytest.mark.parametrize("seed", range(4))
    def test_randomized_schedule_conserves_requests(self, tiny_llama, seed):
        """Property-style drill: a randomized submit/fault schedule
        (valid, over-long and empty prompts; random TTLs; a random NaN
        poke; bounded queue) always satisfies
        submitted == ok+timeout+shed+rejected+quarantined+aborted, leaves
        no slot active, and never retraces the decode step."""
        rng = random.Random(seed)
        inj = ServingFaultInjector(
            nan_logits_at_step=rng.randint(2, 5),
            nan_logits_slot=rng.randint(0, 1),
            deadline_storm_at_step=(
                rng.randint(4, 8) if rng.random() < 0.5 else 0),
        )
        eng = make_engine(
            tiny_llama, max_slots=2, queue_capacity=rng.randint(1, 3),
            strict_submit=False, injector=inj,
        )
        # one long-lived anchor request guarantees decode steps happen
        eng.submit([1, 2], max_new_tokens=rng.randint(6, 12))
        for _ in range(rng.randint(4, 10)):
            kind = rng.random()
            if kind < 0.15:
                eng.submit([])                            # rejected
            elif kind < 0.3:
                eng.submit([1] * 20)                      # rejected
            else:
                eng.submit(
                    [rng.randint(1, 62)
                     for _ in range(rng.randint(1, 6))],
                    max_new_tokens=rng.randint(1, 8),
                    ttl_s=rng.choice([None, None, 1e-9, 5.0]),
                )
            if rng.random() < 0.3:
                eng.step()
        results = eng.run(max_steps=rng.choice([5, 100]))
        assert_conserved(eng)
        assert eng.metrics.decode_steps > 0
        assert eng.decode_compile_count == 1
        counts = Counter(r.outcome for r in results.values())
        snap = eng.metrics.snapshot()
        for outcome in TERMINAL_OUTCOMES:
            assert snap[f"requests_{outcome}"] == counts.get(outcome, 0)

    def test_snapshot_rates(self, tiny_llama):
        """Satellite: the per-outcome counters plus deadline-miss and
        quarantine rates ride snapshot() (and therefore the monitor ring
        buffer + crash reports)."""
        inj = ServingFaultInjector(nan_logits_at_step=2, nan_logits_slot=0)
        eng = make_engine(tiny_llama, max_slots=1, injector=inj)
        eng.submit([1, 2, 3], max_new_tokens=8)
        eng.submit([4, 5], max_new_tokens=1, ttl_s=1e-9)
        eng.run()
        snap = eng.metrics.snapshot()
        assert snap["requests_quarantined"] == 1
        assert snap["requests_timeout"] == 1
        assert snap["quarantine_rate"] == 0.5
        assert snap["deadline_miss_rate"] == 0.5

    def test_outcome_counters_ride_monitor_ring_buffer(self, tiny_llama):
        pytest.importorskip("psutil")
        from scaletorch_tpu.utils.monitor import SystemMonitor

        mon = SystemMonitor(max_records=16)
        eng = make_engine(tiny_llama, max_slots=1, monitor=mon,
                          monitor_every=1)
        eng.submit([1, 2], max_new_tokens=3)
        eng.run()
        assert mon.records
        last = mon.records[-1]
        assert "requests_ok" in last
        assert "deadline_miss_rate" in last


class TestTimingFields:
    def test_partial_results_keep_ttft_and_latency(self, tiny_llama):
        inj = ServingFaultInjector(deadline_storm_at_step=2)
        eng = make_engine(tiny_llama, max_slots=1, injector=inj)
        rid = eng.submit([1, 2, 3], max_new_tokens=20)
        results = eng.run()
        res = results[rid]
        assert res.outcome == "timeout"
        assert res.ttft_s is not None and res.ttft_s >= 0
        assert res.latency_s is not None and res.latency_s >= res.ttft_s

    def test_never_started_results_have_no_ttft(self, tiny_llama):
        eng = make_engine(tiny_llama, max_slots=1)
        rid = eng.submit([1, 2], max_new_tokens=2, ttl_s=1e-9)
        results = eng.run()
        assert results[rid].outcome == "timeout"
        assert results[rid].ttft_s is None
        assert results[rid].latency_s is not None


class TestInjectorConfig:
    def test_from_config_env_parity(self, monkeypatch):
        class Cfg:
            ft_serve_nan_at_step = 3
            ft_serve_nan_slot = 1
            ft_serve_slow_at_step = 0
            ft_serve_slow_seconds = 2.5
            ft_serve_submit_storm_at_step = 7
            ft_serve_submit_storm_count = 4
            ft_serve_deadline_storm_at_step = 0

        inj = ServingFaultInjector.from_config(Cfg())
        assert inj.nan_logits_at_step == 3
        assert inj.nan_logits_slot == 1
        assert inj.submit_storm_at_step == 7
        assert inj.submit_storm_count == 4
        assert inj.slow_decode_seconds == 2.5
        assert inj.active

        # present-wins: an explicit env 0 CANCELS a config-armed drill
        monkeypatch.setenv("SCALETORCH_TPU_FT_SERVE_NAN_STEP", "0")
        monkeypatch.setenv("SCALETORCH_TPU_FT_SERVE_SUBMIT_STORM_STEP", "0")
        monkeypatch.setenv("SCALETORCH_TPU_FT_SERVE_DEADLINE_STORM_STEP", "9")
        inj = ServingFaultInjector.from_config(Cfg())
        assert inj.nan_logits_at_step == 0
        assert inj.submit_storm_at_step == 0
        assert inj.deadline_storm_at_step == 9

    def test_cli_flags_parse(self):
        from scaletorch_tpu.config import parse_args

        cfg = parse_args([
            "--ft_serve_nan_at_step", "5",
            "--ft_serve_submit_storm_at_step", "2",
            "--ft_serve_submit_storm_count", "16",
        ])
        inj = ServingFaultInjector.from_config(cfg)
        assert inj.nan_logits_at_step == 5
        assert inj.submit_storm_at_step == 2
        assert inj.submit_storm_count == 16
