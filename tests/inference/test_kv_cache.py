"""KV-cache containers: shapes, dtypes, sharding specs, write semantics."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scaletorch_tpu.inference.kv_cache import (
    KVCache,
    init_kv_cache,
    init_mla_cache,
    kv_cache_bytes,
    kv_cache_shape,
    kv_cache_shardings,
    kv_cache_specs,
)
from scaletorch_tpu.models.attention.base import AttentionConfig
from scaletorch_tpu.models.gpt_moe import GPTMoEConfig
from scaletorch_tpu.models.layers import write_kv_cache
from scaletorch_tpu.models.llama import LlamaConfig

TINY = LlamaConfig(
    vocab_size=64, hidden_size=32, intermediate_size=64,
    num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
    dtype=jnp.float32,
)


class TestShapes:
    def test_llama_layout(self):
        assert kv_cache_shape(TINY, 2, 16) == (3, 2, 2, 16, 8)

    def test_gpt_moe_layout(self):
        cfg = GPTMoEConfig(block_size=32, n_layer=2, n_head=4, n_embd=64)
        assert kv_cache_shape(cfg, 2, 32) == (2, 2, 4, 32, 16)

    def test_unknown_config_raises(self):
        with pytest.raises(TypeError, match="no KV-cache layout"):
            kv_cache_shape(object(), 1, 8)

    def test_init_zeroed_in_compute_dtype(self):
        cache = init_kv_cache(TINY, 2, 16)
        assert isinstance(cache, KVCache)
        assert cache.k.shape == (3, 2, 2, 16, 8)
        assert cache.k.dtype == jnp.float32
        assert not np.any(np.asarray(cache.v))

    def test_bytes_accounting(self):
        assert kv_cache_bytes(TINY, 2, 16) == 2 * 3 * 2 * 2 * 16 * 8 * 4
        assert kv_cache_bytes(TINY, 2, 16, dtype=jnp.bfloat16) == \
            kv_cache_bytes(TINY, 2, 16) // 2

    def test_mla_latent_only(self):
        acfg = AttentionConfig(embed_dim=64, num_heads=8, kv_lora_rank=16)
        cache = init_mla_cache(acfg, 2, 12)
        assert cache.latent.shape == (2, 12, 16)


class TestSharding:
    def test_specs_head_axis_over_tp(self):
        specs = kv_cache_specs(tp_axis="tp")
        assert specs.k == jax.sharding.PartitionSpec(None, None, "tp", None, None)
        assert specs.k == specs.v

    def test_sharded_init_on_virtual_mesh(self, mm_factory):
        mm = mm_factory(tp=2, dp=4)
        shardings = kv_cache_shardings(mm.mesh, tp_axis="tp")
        cache = init_kv_cache(TINY, 2, 16, sharding=shardings)
        # KV-head axis (2) split over tp=2
        assert cache.k.sharding.spec[2] == "tp"

    def test_batch_axis_sharding(self, mm_factory):
        mm = mm_factory(tp=2, dp=4)
        shardings = kv_cache_shardings(mm.mesh, tp_axis="tp", batch_axis="dp")
        cache = init_kv_cache(TINY, 4, 16, sharding=shardings)
        assert cache.k.sharding.spec[1] == "dp"


class TestWriteKvCache:
    def test_per_slot_offsets(self):
        cache = jnp.zeros((2, 1, 8, 2))
        new = jnp.ones((2, 1, 3, 2))
        out = write_kv_cache(cache, new, jnp.array([0, 4]))
        assert np.asarray(out[0, 0, :3]).all() and not np.asarray(out[0, 0, 3:]).any()
        assert np.asarray(out[1, 0, 4:7]).all() and not np.asarray(out[1, 0, :4]).any()

    def test_write_mask_protects_slots(self):
        cache = jnp.full((2, 1, 8, 2), 7.0)
        new = jnp.ones((2, 1, 3, 2))
        out = write_kv_cache(cache, new, jnp.array([0, 0]),
                             jnp.array([True, False]))
        assert np.asarray(out[0, 0, 0, 0]) == 1.0
        np.testing.assert_array_equal(np.asarray(out[1]), 7.0)
