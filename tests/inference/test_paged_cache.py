"""Paged KV cache primitives (ISSUE 10): allocator conservation under
randomized admit/retire/quarantine schedules, radix-tree prefix
correctness (longest match, page-boundary splits, refcount-gated
eviction), the page scatter/gather pair against the dense cache ops,
the Pallas paged-decode kernel in interpret mode against the lax
fallback oracle, the paged teacher-forced parity harness, and the
layout-aware ``kv_cache_bytes`` fix. Quick tier, CPU.
"""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scaletorch_tpu.inference.decode import (
    teacher_forced_decode,
    teacher_forced_decode_paged,
)
from scaletorch_tpu.inference.kv_cache import (
    PageAllocator,
    RadixPrefixCache,
    kv_cache_bytes,
    kv_cache_shape,
    paged_kv_cache_shape,
)
from scaletorch_tpu.models import llama, qwen3
from scaletorch_tpu.models.layers import cached_sdpa_attention, write_kv_cache
from scaletorch_tpu.ops.pallas.paged_attention import (
    TRASH_PAGE,
    paged_attention,
    paged_gather_kv,
    paged_write_kv,
    pallas_paged_decode_attention,
)

TINY = dict(
    vocab_size=64, hidden_size=32, intermediate_size=64,
    num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
    dtype=jnp.float32,
)


class TestPageAllocator:
    def test_alloc_free_roundtrip(self):
        al = PageAllocator(8)
        assert al.capacity == 7  # page 0 reserved
        pages = al.alloc(3)
        assert len(pages) == 3 and TRASH_PAGE not in pages
        assert al.free_count == 4 and al.used_count == 3
        for p in pages:
            assert al.refcount(p) == 1
            al.release(p)
        assert al.free_count == al.capacity
        al.check_conservation()

    def test_alloc_is_all_or_nothing(self):
        al = PageAllocator(4)
        assert al.alloc(5) is None
        assert al.free_count == 3  # nothing was handed out
        al.check_conservation()

    def test_double_free_raises(self):
        al = PageAllocator(4)
        (p,) = al.alloc(1)
        al.release(p)
        with pytest.raises(ValueError, match="double free"):
            al.release(p)

    def test_foreign_retain_raises(self):
        al = PageAllocator(4)
        with pytest.raises(ValueError, match="unallocated"):
            al.retain(1)

    def test_refcount_sharing(self):
        al = PageAllocator(4)
        (p,) = al.alloc(1)
        al.retain(p)  # a sharing slot
        al.release(p)
        assert al.refcount(p) == 1  # still allocated
        assert al.free_count == 2
        al.release(p)
        assert al.refcount(p) == 0
        assert al.free_count == 3
        al.check_conservation()

    def test_pool_must_cover_reserved(self):
        with pytest.raises(ValueError, match="at least"):
            PageAllocator(1)

    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    def test_conservation_under_random_schedule(self, seed):
        """PR 7's outcome-conservation style for pages: across a
        randomized admit/share/register/retire/quarantine/evict schedule
        no page leaks, none is double-freed, and draining everything
        returns the pool to full capacity."""
        rng = random.Random(seed)
        al = PageAllocator(32)
        radix_refs: list[int] = []   # the tree's own references
        live: list[list[int]] = []   # per-request page references
        for _ in range(300):
            op = rng.random()
            if op < 0.35:  # admit: allocate a few pages
                pages = al.alloc(rng.randint(1, 4))
                if pages is not None:
                    # maybe share an already-registered page too
                    if radix_refs and rng.random() < 0.5:
                        shared = rng.choice(radix_refs)
                        al.retain(shared)
                        pages.append(shared)
                    live.append(pages)
            elif op < 0.55 and live:  # register some pages in the tree
                req = rng.choice(live)
                for p in req[: rng.randint(0, len(req))]:
                    if al.refcount(p) > 0:
                        al.retain(p)
                        radix_refs.append(p)
            elif op < 0.85 and live:  # retire (ok or quarantined alike)
                req = live.pop(rng.randrange(len(live)))
                for p in req:
                    al.release(p)
            elif radix_refs:  # evict one tree reference
                al.release(radix_refs.pop(rng.randrange(len(radix_refs))))
            al.check_conservation()
        for req in live:
            for p in req:
                al.release(p)
        for p in radix_refs:
            al.release(p)
        al.check_conservation()
        assert al.free_count == al.capacity


def _radix(num_pages=32, page_size=4):
    al = PageAllocator(num_pages)
    rx = RadixPrefixCache(page_size, al.retain, al.release, al.refcount)
    return al, rx


class TestRadixPrefixCache:
    def test_longest_prefix_match_is_page_aligned(self):
        al, rx = _radix()
        pages = al.alloc(2)
        rx.insert(list(range(8)), pages)
        n, got = rx.match(list(range(8)) + [99, 98])
        assert n == 8 and got == pages
        n, got = rx.match(list(range(7)))  # partial page never matches
        assert n == 4 and got == pages[:1]
        n, got = rx.match([9, 9, 9, 9])
        assert (n, got) == (0, [])

    def test_page_boundary_split(self):
        """Two prompts sharing their first page diverge at the boundary:
        the tree splits there and each keeps its own second page."""
        al, rx = _radix()
        a = al.alloc(2)
        b = al.alloc(1)
        rx.insert([1, 2, 3, 4, 5, 6, 7, 8], a)
        rx.insert([1, 2, 3, 4, 9, 9, 9, 9], [a[0], b[0]])
        assert len(rx) == 3  # shared head + two tails
        assert rx.match([1, 2, 3, 4, 5, 6, 7, 8])[1] == a
        assert rx.match([1, 2, 3, 4, 9, 9, 9, 9])[1] == [a[0], b[0]]
        # the shared head holds ONE tree reference, not two
        assert al.refcount(a[0]) == 2  # slot + tree

    def test_insert_validation(self):
        al, rx = _radix()
        pages = al.alloc(1)
        with pytest.raises(ValueError, match="page-aligned"):
            rx.insert([1, 2, 3], pages)
        with pytest.raises(ValueError, match="one page per"):
            rx.insert([1, 2, 3, 4, 5, 6, 7, 8], pages)

    def test_first_writer_wins(self):
        al, rx = _radix()
        a = al.alloc(1)
        b = al.alloc(1)
        assert rx.insert([1, 2, 3, 4], a) == 1
        assert rx.insert([1, 2, 3, 4], b) == 0  # duplicate stays private
        assert rx.match([1, 2, 3, 4])[1] == a
        assert al.refcount(b[0]) == 1  # no tree reference taken

    def test_eviction_only_at_tree_refcount(self):
        al, rx = _radix()
        pages = al.alloc(1)
        rx.insert([1, 2, 3, 4], pages)
        assert rx.evict(1) == 0  # pinned by the allocating slot
        al.release(pages[0])     # slot retires
        assert rx.evict(1) == 1
        assert al.free_count == al.capacity
        al.check_conservation()

    def test_eviction_is_lru(self):
        al, rx = _radix()
        a = al.alloc(1)
        b = al.alloc(1)
        rx.insert([1, 1, 1, 1], a)
        rx.insert([2, 2, 2, 2], b)
        al.release(a[0])
        al.release(b[0])
        rx.match([1, 1, 1, 1])  # touch a: b becomes the LRU leaf
        assert rx.evict(1) == 1
        assert rx.match([2, 2, 2, 2]) == (0, [])
        assert rx.match([1, 1, 1, 1])[0] == 4

    def test_inner_nodes_evict_after_children(self):
        al, rx = _radix()
        pages = al.alloc(3)
        rx.insert(list(range(12)), pages)
        for p in pages:
            al.release(p)
        assert rx.evict(10) == 3  # leaf, then its parent, then the root's child
        assert len(rx) == 0
        assert al.free_count == al.capacity


class TestPagedPrimitives:
    B, H, S_MAX, D, PS = 2, 2, 16, 8, 4

    def _pool_and_tables(self, key=0):
        mp = self.S_MAX // self.PS
        pool = jax.random.normal(
            jax.random.PRNGKey(key),
            (self.B * mp + 1, self.H, self.PS, self.D), jnp.float32)
        tables = (np.arange(self.B * mp, dtype=np.int32) + 1).reshape(
            self.B, mp)
        return pool, jnp.asarray(tables)

    def test_write_then_gather_matches_dense_write(self):
        k = jax.random.PRNGKey(1)
        new = jax.random.normal(k, (self.B, self.H, 3, self.D), jnp.float32)
        starts = jnp.asarray([2, 9], jnp.int32)
        positions = starts[:, None] + jnp.arange(3)[None, :]
        dense = jnp.zeros((self.B, self.H, self.S_MAX, self.D), jnp.float32)
        dense = write_kv_cache(dense, new, starts)
        pool = jnp.zeros(
            (self.B * (self.S_MAX // self.PS) + 1, self.H, self.PS, self.D),
            jnp.float32)
        _, tables = self._pool_and_tables()
        pool = paged_write_kv(pool, new, positions, tables, self.PS)
        view = paged_gather_kv(pool, tables)
        assert jnp.array_equal(view[:, :, : self.S_MAX], dense)

    def test_write_mask_redirects_to_trash(self):
        pool, tables = self._pool_and_tables()
        before = pool
        new = jnp.ones((self.B, self.H, 1, self.D), jnp.float32) * 7.0
        positions = jnp.asarray([[0], [0]], jnp.int32)
        pool = paged_write_kv(pool, new, positions, tables, self.PS,
                              write_mask=jnp.asarray([False, True]))
        # slot 1's page took the write, slot 0's pages are untouched and
        # the masked write landed on the TRASH page
        assert jnp.array_equal(pool[tables[0, 0]], before[tables[0, 0]])
        assert not jnp.array_equal(pool[tables[1, 0]], before[tables[1, 0]])
        assert not jnp.array_equal(pool[TRASH_PAGE], before[TRASH_PAGE])

    def test_positions_past_table_go_to_trash(self):
        pool, tables = self._pool_and_tables()
        before = pool
        new = jnp.full((self.B, self.H, 1, self.D), 5.0, jnp.float32)
        positions = jnp.full((self.B, 1), self.S_MAX + 3, jnp.int32)
        pool = paged_write_kv(pool, new, positions, tables, self.PS)
        for b in range(self.B):
            for t in np.asarray(tables[b]):
                assert jnp.array_equal(pool[t], before[t])

    def test_fallback_attention_bit_matches_dense(self):
        pool_k, tables = self._pool_and_tables(0)
        pool_v, _ = self._pool_and_tables(1)
        q = jax.random.normal(jax.random.PRNGKey(2),
                              (self.B, 4, 1, self.D), jnp.float32)
        pos = jnp.asarray([[5], [13]], jnp.int32)
        out = paged_attention(q, pool_k, pool_v, tables, pos,
                              page_size=self.PS, seq_limit=self.S_MAX,
                              kernel=False)
        dense = cached_sdpa_attention(
            q, paged_gather_kv(pool_k, tables)[:, :, : self.S_MAX],
            paged_gather_kv(pool_v, tables)[:, :, : self.S_MAX], pos)
        assert jnp.array_equal(out, dense)

    def test_pallas_kernel_interpret_matches_fallback(self):
        pool_k, tables = self._pool_and_tables(0)
        pool_v, _ = self._pool_and_tables(1)
        q = jax.random.normal(jax.random.PRNGKey(3),
                              (self.B, 4, self.D), jnp.float32)
        pos = jnp.asarray([2, 14], jnp.int32)
        out_k = pallas_paged_decode_attention(
            q, pool_k, pool_v, tables, pos, interpret=True)
        out_f = cached_sdpa_attention(
            q[:, :, None], paged_gather_kv(pool_k, tables),
            paged_gather_kv(pool_v, tables), pos[:, None])[:, :, 0]
        np.testing.assert_allclose(np.asarray(out_k), np.asarray(out_f),
                                   atol=2e-6)

    def test_kernel_requires_single_token(self):
        pool_k, tables = self._pool_and_tables()
        q = jnp.zeros((self.B, 4, 3, self.D), jnp.float32)
        with pytest.raises(ValueError, match="single-token"):
            paged_attention(q, pool_k, pool_k, tables,
                            jnp.zeros((self.B, 3), jnp.int32),
                            page_size=self.PS, kernel=True)

    def test_kernel_rejects_ragged_gqa(self):
        pool_k, tables = self._pool_and_tables()
        q = jnp.zeros((self.B, 3, self.D), jnp.float32)  # 3 q-heads over 2 kv
        with pytest.raises(ValueError, match="not a multiple"):
            pallas_paged_decode_attention(
                q, pool_k, pool_k, tables, jnp.zeros((self.B,), jnp.int32))


class TestTeacherForcedPagedParity:
    """The paged read/write path reproduces the dense cache's logits
    bit-for-bit under teacher forcing — same operand shapes (seq_limit
    crop), same values, same reduction."""

    def _check(self, cfg, init, page_size):
        params = init(jax.random.PRNGKey(0), cfg)
        ids = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0,
                                 cfg.vocab_size)
        dense = teacher_forced_decode(params, cfg, ids, max_seq=16,
                                      prefill_len=5)
        paged = teacher_forced_decode_paged(
            params, cfg, ids, page_size=page_size, max_seq=16,
            prefill_len=5)
        assert jnp.array_equal(dense, paged)

    @pytest.mark.parametrize("page_size", [4, 5, 16])
    def test_llama_gqa(self, page_size):
        self._check(llama.LlamaConfig(**TINY), llama.init_params, page_size)

    def test_qwen3(self):
        self._check(qwen3.Qwen3Config(**{**TINY, "head_dim": 16}),
                    qwen3.init_params, 4)


class TestCacheBytesLayouts:
    """Satellite fix: ``kv_cache_bytes`` reports the layout actually
    deployed, not always the dense one."""

    def test_dense_unchanged(self):
        cfg = llama.LlamaConfig(**TINY)
        shape = kv_cache_shape(cfg, 4, 128)
        n = int(np.prod(shape))
        assert kv_cache_bytes(cfg, 4, 128, jnp.float32) == 2 * n * 4

    def test_paged_pool_bytes(self):
        cfg = llama.LlamaConfig(**TINY)
        shape = paged_kv_cache_shape(cfg, 33, 16)
        n = int(np.prod(shape))
        got = kv_cache_bytes(cfg, 4, 128, jnp.float32, layout="paged",
                             page_size=16, num_pages=33)
        assert got == 2 * n * 4

    def test_paged_defaults_to_dense_equivalent_pool(self):
        cfg = llama.LlamaConfig(**TINY)
        # batch * ceil(max_seq / page_size) + 1 trash page
        auto = kv_cache_bytes(cfg, 4, 120, jnp.float32, layout="paged",
                              page_size=16)
        explicit = kv_cache_bytes(cfg, 4, 120, jnp.float32, layout="paged",
                                  page_size=16, num_pages=4 * 8 + 1)
        assert auto == explicit

    def test_invalid_layouts_raise(self):
        cfg = llama.LlamaConfig(**TINY)
        with pytest.raises(ValueError, match="unknown cache layout"):
            kv_cache_bytes(cfg, 1, 8, layout="ragged")
        with pytest.raises(ValueError, match="page_size"):
            kv_cache_bytes(cfg, 1, 8, layout="paged")

    def test_engine_pool_matches_admission_math(self):
        """The ISSUE 15 unification: the bytes the engine's admission /
        shedding math reasons about (``kv_cache_bytes``) and the bytes
        the engine actually allocated (``cache_nbytes`` over the live
        pool/cache) must agree exactly, for both layouts — the jaxlint
        memory tier's ST1005 pins the same identity over the COMPILED
        audit entries, so bench_decode's HBM column can never drift."""
        from scaletorch_tpu.inference import InferenceEngine, SamplingParams
        from scaletorch_tpu.inference.kv_cache import cache_nbytes

        cfg = llama.LlamaConfig(**TINY)
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        paged = InferenceEngine(
            params, cfg, sampling=SamplingParams(temperature=0.0),
            max_slots=2, max_seq=16, cache_layout="paged", page_size=4,
        )
        assert cache_nbytes(paged.cache) == kv_cache_bytes(
            cfg, 2, 16, cfg.dtype, layout="paged", page_size=4,
            num_pages=paged.num_pages)
        dense = InferenceEngine(
            params, cfg, sampling=SamplingParams(temperature=0.0),
            max_slots=2, max_seq=16,
        )
        assert cache_nbytes(dense.cache) == kv_cache_bytes(
            cfg, 2, 16, cfg.dtype)
