"""Paged-cache engine (ISSUE 10): bit-identity with the dense engine
across llama-GQA / qwen3 / qwen3-MoE schedules (including a PR 7
quarantine drill), one-compile discipline through admissions + prefix
hits + quarantine clears + frees, counter-attested prefix reuse,
page-budget admission, conservation, and TP-sharded paged serving on
the virtual mesh. Quick tier, CPU.
"""

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from scaletorch_tpu.inference import (
    InferenceEngine,
    SamplingParams,
    ServingFaultInjector,
)
from scaletorch_tpu.models import llama, qwen3, qwen3_moe

TINY = dict(
    vocab_size=64, hidden_size=32, intermediate_size=64,
    num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
    dtype=jnp.float32,
)
GREEDY = SamplingParams(temperature=0.0)

SCHEDULE = [([1, 2, 3], 3), ([9, 8], 5), ([4, 5, 6, 7], 2), ([11], 6),
            ([1, 2, 3, 5], 4)]


@pytest.fixture(scope="module")
def tiny_llama():
    cfg = llama.LlamaConfig(**TINY)
    return cfg, llama.init_params(jax.random.PRNGKey(0), cfg)


def serve(params, cfg, layout, schedule=SCHEDULE, *, injector=None,
          prefill_len=8, **kw):
    eng = InferenceEngine(
        params, cfg, max_slots=2, max_seq=32, prefill_len=prefill_len,
        sampling=GREEDY, cache_layout=layout, injector=injector, **kw)
    ids = [eng.submit(p, max_new_tokens=n) for p, n in schedule]
    results = eng.run()
    return eng, [results[i] for i in ids]


def assert_pages_conserved(eng):
    """After a full drain every page reference left belongs to the radix
    tree; evicting it all returns the pool to capacity."""
    eng.allocator.check_conservation()
    assert all(not s.active for s in eng._slots)
    if eng.radix is not None:
        eng.radix.evict(eng.num_pages)
    assert eng.allocator.free_count == eng.allocator.capacity


class TestPagedMatchesDense:
    def _check(self, cfg, params, page_size=4):
        ed, dense = serve(params, cfg, "dense")
        ep, paged = serve(params, cfg, "paged", page_size=page_size)
        for d, p in zip(dense, paged):
            assert d.tokens == p.tokens
            assert d.finish_reason == p.finish_reason
        assert ep.decode_compile_count == 1
        assert ep.prefill_compile_count == 1
        assert_pages_conserved(ep)

    def test_llama_gqa(self, tiny_llama):
        self._check(*tiny_llama)

    def test_llama_page_size_misaligned_with_seq(self, tiny_llama):
        cfg, params = tiny_llama
        self._check(cfg, params, page_size=5)  # max_seq % page_size != 0

    def test_qwen3(self):
        cfg = qwen3.Qwen3Config(**{**TINY, "head_dim": 16})
        self._check(cfg, qwen3.init_params(jax.random.PRNGKey(0), cfg))

    def test_qwen3_moe(self):
        cfg = qwen3_moe.Qwen3MoEConfig(
            **{**TINY, "head_dim": 16}, moe_intermediate_size=48,
            num_experts=4, num_experts_per_tok=2, capacity_factor=2.0,
            tie_word_embeddings=False,
        )
        self._check(cfg, qwen3_moe.init_params(jax.random.PRNGKey(0), cfg))

    def test_quarantine_drill_bit_identity(self, tiny_llama):
        """PR 7 drill on the paged layout: a poisoned slot quarantines,
        its NEIGHBOUR's greedy output stays bit-identical to both the
        fault-free paged run and the dense engine under the same drill,
        and nothing retraces through the page-clear."""
        cfg, params = tiny_llama
        schedule = [([1, 2, 3], 8), ([7, 8, 9, 10], 8)]
        _, clean = serve(params, cfg, "paged", schedule, page_size=4)
        ep, paged = serve(
            params, cfg, "paged", schedule, page_size=4,
            injector=ServingFaultInjector(nan_logits_at_step=3,
                                          nan_logits_slot=0))
        _, dense = serve(
            params, cfg, "dense", schedule,
            injector=ServingFaultInjector(nan_logits_at_step=3,
                                          nan_logits_slot=0))
        assert paged[0].outcome == "quarantined"
        assert paged[0].tokens == clean[0].tokens[: len(paged[0].tokens)]
        assert paged[1].outcome == "ok"
        assert paged[1].tokens == clean[1].tokens  # neighbour unaffected
        assert paged[0].tokens == dense[0].tokens
        assert paged[1].tokens == dense[1].tokens
        assert ep.decode_compile_count == 1
        assert ep.prefill_compile_count == 1
        assert_pages_conserved(ep)

    def test_slot_reuse_after_quarantine_is_clean(self, tiny_llama):
        """The quarantined request's mutable pages are cleared and
        released; the next occupant of the pool sees none of them."""
        cfg, params = tiny_llama
        inj = ServingFaultInjector(nan_logits_at_step=2, nan_logits_slot=0)
        eng = InferenceEngine(params, cfg, max_slots=1, max_seq=32,
                              prefill_len=8, sampling=GREEDY,
                              cache_layout="paged", page_size=4,
                              injector=inj)
        poisoned = eng.submit([1, 2, 3], max_new_tokens=8)
        reused = eng.submit([9, 8, 7], max_new_tokens=4)
        results = eng.run()
        assert results[poisoned].outcome == "quarantined"
        assert results[reused].outcome == "ok"
        e2, fresh = serve(params, cfg, "paged", [([9, 8, 7], 4)],
                          page_size=4)
        assert results[reused].tokens == fresh[0].tokens
        assert eng.decode_compile_count == 1
        assert_pages_conserved(eng)


class TestPrefixSharing:
    SYS = [7, 7, 7, 7, 3, 3, 3, 3]  # two full pages at page_size=4

    def test_second_request_reuses_prefix_pages(self, tiny_llama):
        """Counter-attested reuse: the second request with the shared
        system prompt prefills ZERO forward tokens for the shared pages
        (prefill_tokens_saved == shared length), physically shares the
        first request's frozen pages, and its output is bit-identical to
        the dense engine that re-prefilled everything."""
        cfg, params = tiny_llama
        eng = InferenceEngine(params, cfg, max_slots=2, max_seq=32,
                              prefill_len=12, sampling=GREEDY,
                              cache_layout="paged", page_size=4)
        eng.submit(self.SYS + [1], max_new_tokens=4)
        eng.run()
        matched, frozen_pages = eng.radix.match(self.SYS)
        assert matched == len(self.SYS)  # both prompt pages registered
        assert eng.metrics.prefill_tokens_saved == 0
        r2 = eng.submit(self.SYS + [2], max_new_tokens=4)
        eng.step()  # admission tick
        assert eng.metrics.prefix_hits == 1
        assert eng.metrics.prefill_tokens_saved == len(self.SYS)
        # the hit is physical: slot's leading table entries ARE the
        # first request's frozen pages, refcounted tree + slot
        slot = next(i for i, s in enumerate(eng._slots) if s.active)
        assert list(eng._tables[slot, :2]) == frozen_pages
        assert all(eng.allocator.refcount(int(p)) == 2
                   for p in frozen_pages)
        results = eng.run()
        _, dense = serve(params, cfg, "dense",
                         [(self.SYS + [1], 4), (self.SYS + [2], 4)],
                         prefill_len=12)
        assert results[r2].tokens == dense[1].tokens
        assert eng.decode_compile_count == 1
        assert eng.prefill_compile_count == 1
        snap = eng.metrics.snapshot()
        assert snap["prefix_hit_rate"] == 0.5  # 1 hit / 2 admissions
        assert snap["prefill_tokens_saved"] == len(self.SYS)
        assert_pages_conserved(eng)

    def test_full_prefix_hit_still_prefills_one_token(self, tiny_llama):
        """A prompt that is ENTIRELY cached page-aligned still runs its
        last page through prefill — the first sampled token needs the
        logits at prompt_len - 1."""
        cfg, params = tiny_llama
        eng = InferenceEngine(params, cfg, max_slots=1, max_seq=32,
                              prefill_len=8, sampling=GREEDY,
                              cache_layout="paged", page_size=4)
        r1 = eng.submit(list(self.SYS), max_new_tokens=3)
        first = eng.run()[r1].tokens
        r2 = eng.submit(list(self.SYS), max_new_tokens=3)
        results = eng.run()
        assert results[r2].tokens == first
        # only the first page is shared; the boundary page re-prefills
        assert eng.metrics.prefill_tokens_saved == 4
        assert_pages_conserved(eng)

    def test_prefix_cache_off_still_correct(self, tiny_llama):
        cfg, params = tiny_llama
        ep, paged = serve(params, cfg, "paged", page_size=4,
                          prefix_cache=False)
        _, dense = serve(params, cfg, "dense")
        assert [r.tokens for r in paged] == [r.tokens for r in dense]
        assert ep.radix is None
        assert ep.metrics.prefix_hits == 0
        assert_pages_conserved(ep)


class TestPageBudgetAdmission:
    def test_admission_waits_for_pages_then_recovers(self, tiny_llama):
        """A pool that covers only one request at a time serializes the
        two requests instead of deadlocking or corrupting — page-budget
        admission, not slot arithmetic."""
        cfg, params = tiny_llama
        # each request needs ceil((3 + 8) / 4) = 3 pages; pool holds 4
        eng = InferenceEngine(params, cfg, max_slots=2, max_seq=32,
                              prefill_len=8, sampling=GREEDY,
                              cache_layout="paged", page_size=4,
                              num_pages=5, prefix_cache=False)
        a = eng.submit([1, 2, 3], max_new_tokens=8)
        b = eng.submit([7, 8, 9], max_new_tokens=8)
        eng.step()
        # only one admitted: the second waits on the page budget
        assert sum(s.active for s in eng._slots) == 1
        assert eng.metrics.queue_depth == 1
        results = eng.run()
        _, dense = serve(params, cfg, "dense",
                         [([1, 2, 3], 8), ([7, 8, 9], 8)])
        assert results[a].tokens == dense[0].tokens
        assert results[b].tokens == dense[1].tokens
        assert eng.decode_compile_count == 1
        assert_pages_conserved(eng)

    def test_eviction_unblocks_admission(self, tiny_llama):
        """Radix-held pages are reclaimed when a new request needs the
        budget: the tree evicts unpinned leaves instead of blocking."""
        cfg, params = tiny_llama
        eng = InferenceEngine(params, cfg, max_slots=1, max_seq=32,
                              prefill_len=8, sampling=GREEDY,
                              cache_layout="paged", page_size=4,
                              num_pages=5)
        eng.submit([1, 2, 3, 4, 5], max_new_tokens=3)  # registers a page
        eng.run()
        assert eng.allocator.used_count > 0  # tree still holds the page
        r = eng.submit([9, 9, 9], max_new_tokens=8)    # needs 3 of 4 pages
        results = eng.run()
        assert results[r].outcome == "ok"
        assert_pages_conserved(eng)

    def test_impossible_request_rejected_at_submit(self, tiny_llama):
        cfg, params = tiny_llama
        eng = InferenceEngine(params, cfg, max_slots=1, max_seq=32,
                              prefill_len=8, sampling=GREEDY,
                              cache_layout="paged", page_size=4,
                              num_pages=3)
        with pytest.raises(ValueError, match="pages"):
            eng.submit([1, 2, 3], max_new_tokens=20)
        lax = InferenceEngine(params, cfg, max_slots=1, max_seq=32,
                              prefill_len=8, sampling=GREEDY,
                              cache_layout="paged", page_size=4,
                              num_pages=3, strict_submit=False)
        rid = lax.submit([1, 2, 3], max_new_tokens=20)
        assert lax.result(rid).outcome == "rejected"

    def test_bad_layout_and_page_size_raise(self, tiny_llama):
        cfg, params = tiny_llama
        with pytest.raises(ValueError, match="cache_layout"):
            InferenceEngine(params, cfg, cache_layout="ragged")
        with pytest.raises(ValueError, match="page_size"):
            InferenceEngine(params, cfg, cache_layout="paged", page_size=0)


class TestShardedPagedServing:
    def test_tp_sharded_pool_matches_unsharded(self, tiny_llama, mm_factory):
        """ISSUE 10 acceptance: TP-sharded paged serving (pool KV heads
        over tp, GSPMD steps) equals the unsharded paged engine
        bit-for-bit on the virtual mesh — same oracle style as PR 3."""
        from scaletorch_tpu.parallel.tensor_parallel import llama_param_specs

        cfg, params = tiny_llama
        e0, expected = serve(params, cfg, "paged", page_size=4)
        mm = mm_factory(tp=2, dp=4)
        specs = llama_param_specs(cfg, tp_axis="tp")
        shardings = jax.tree.map(
            lambda s: NamedSharding(mm.mesh, s), specs,
            is_leaf=lambda x: isinstance(x, P),
        )
        params_sh = jax.tree.map(jax.device_put, params, shardings)
        eng = InferenceEngine(params_sh, cfg, max_slots=2, max_seq=32,
                              prefill_len=8, mesh=mm.mesh, tp_axis="tp",
                              sampling=GREEDY, cache_layout="paged",
                              page_size=4)
        assert eng.cache.k.sharding.spec[2] == "tp"
        ids = [eng.submit(p, max_new_tokens=n) for p, n in SCHEDULE]
        results = eng.run()
        for rid, exp in zip(ids, expected):
            assert results[rid].tokens == exp.tokens
        assert eng.decode_compile_count == 1
        assert_pages_conserved(eng)


class TestPagedMetrics:
    def test_page_gauges_move_and_export(self, tiny_llama):
        cfg, params = tiny_llama
        eng = InferenceEngine(params, cfg, max_slots=2, max_seq=32,
                              prefill_len=8, sampling=GREEDY,
                              cache_layout="paged", page_size=4)
        snap0 = eng.metrics.snapshot()
        assert snap0["pages_in_use"] == 0
        assert snap0["page_pool_free"] == eng.allocator.capacity
        eng.submit([1, 2, 3], max_new_tokens=4)
        eng.step()
        snap1 = eng.metrics.snapshot()
        assert snap1["pages_in_use"] > 0
        assert snap1["page_pool_free"] < snap0["page_pool_free"]
        eng.run()

    def test_dense_snapshot_keeps_keys_zeroed(self, tiny_llama):
        """The new keys ride every snapshot (telemetry JSONL/Prometheus
        schema is layout-independent); dense engines report zeros."""
        cfg, params = tiny_llama
        eng, _ = serve(params, cfg, "dense", [([1, 2], 2)])
        snap = eng.metrics.snapshot()
        assert snap["pages_in_use"] == 0
        assert snap["page_pool_free"] == 0
        assert snap["prefix_hit_rate"] == 0.0
        assert snap["prefill_tokens_saved"] == 0
