"""Sampling knobs: greedy / temperature / top-k / top-p, per-slot keys."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scaletorch_tpu.inference.sampling import (
    SamplingParams,
    _filter_top_k,
    _filter_top_p,
    sample,
    sample_one,
    slot_keys,
)


class TestParams:
    def test_validation(self):
        with pytest.raises(ValueError, match="temperature"):
            SamplingParams(temperature=-1.0)
        with pytest.raises(ValueError, match="top_k"):
            SamplingParams(top_k=-2)
        with pytest.raises(ValueError, match="top_p"):
            SamplingParams(top_p=0.0)
        with pytest.raises(ValueError, match="top_p"):
            SamplingParams(top_p=1.5)

    def test_greedy_flag(self):
        assert SamplingParams(temperature=0.0).greedy
        assert not SamplingParams(temperature=0.7).greedy


class TestFilters:
    LOGITS = jnp.array([1.0, 3.0, 2.0, -1.0])

    def test_top_k_keeps_k_highest(self):
        out = np.asarray(_filter_top_k(self.LOGITS, 2))
        assert np.isfinite(out[[1, 2]]).all()
        assert (out[[0, 3]] < -1e30).all()

    def test_top_k_disabled(self):
        np.testing.assert_array_equal(
            np.asarray(_filter_top_k(self.LOGITS, 0)), np.asarray(self.LOGITS))
        np.testing.assert_array_equal(
            np.asarray(_filter_top_k(self.LOGITS, 10)), np.asarray(self.LOGITS))

    def test_top_p_keeps_nucleus(self):
        # softmax([1,3,2,-1]) ~ [0.09, 0.66, 0.24, 0.01]: p=0.8 keeps {3, 2}
        out = np.asarray(_filter_top_p(self.LOGITS, 0.8))
        assert np.isfinite(out[[1, 2]]).all()
        assert (out[[0, 3]] < -1e30).all()

    def test_top_p_tiny_keeps_argmax(self):
        out = np.asarray(_filter_top_p(self.LOGITS, 1e-6))
        assert np.isfinite(out[1])
        assert (np.delete(out, 1) < -1e30).all()


class TestSample:
    LOGITS = jnp.array([[1.0, 5.0, 2.0], [4.0, 0.0, 1.0]])

    def test_greedy_is_argmax(self):
        keys = jnp.stack([jax.random.PRNGKey(0)] * 2)
        out = sample(self.LOGITS, keys, SamplingParams(temperature=0.0))
        np.testing.assert_array_equal(np.asarray(out), [1, 0])

    def test_top_k_1_equals_greedy(self):
        keys = jnp.stack([jax.random.PRNGKey(i) for i in range(2)])
        out = sample(self.LOGITS, keys,
                     SamplingParams(temperature=1.0, top_k=1))
        np.testing.assert_array_equal(np.asarray(out), [1, 0])

    def test_sampled_tokens_respect_filter(self):
        # top_k=2 on [1,5,2] can never emit index 0
        params = SamplingParams(temperature=1.0, top_k=2)
        for seed in range(20):
            tok = sample_one(self.LOGITS[0], jax.random.PRNGKey(seed), params)
            assert int(tok) in (1, 2)

    def test_per_slot_keys_decorrelate(self):
        logits = jnp.zeros((2, 1024))  # uniform: same key => same sample
        same = jnp.stack([jax.random.PRNGKey(0)] * 2)
        diff = jnp.stack([jax.random.PRNGKey(0), jax.random.PRNGKey(1)])
        s_same = np.asarray(sample(logits, same, SamplingParams()))
        s_diff = np.asarray(sample(logits, diff, SamplingParams()))
        assert s_same[0] == s_same[1]
        assert s_diff[0] != s_diff[1]

    def test_slot_keys_deterministic_per_position(self):
        base = jnp.stack([jax.random.PRNGKey(3)] * 2)
        k1 = slot_keys(base, jnp.array([4, 5]))
        k2 = slot_keys(base, jnp.array([4, 5]))
        np.testing.assert_array_equal(np.asarray(k1), np.asarray(k2))
        assert not np.array_equal(np.asarray(k1[0]), np.asarray(k1[1]))
