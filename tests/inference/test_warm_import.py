"""Engine half of warm rejoin: export/import of frozen prefix pages.

The acceptance attestation: a recipient engine warmed with a donor's
prefix pages serves its FIRST shared-prefix request with a physical
prefix hit and bit-identical greedy output — with ``decode_compile_count
== 1`` on both ends (the import rides the existing jitted fill step; a
cache-shaped fill value is a new argument structure, not a retrace of
the audited decode/prefill entries). Conservation: donor refcounts never
move across an export; an aborted/partial import releases every
allocation it made; warmed pages are frozen-from-birth and evictable at
zero like any cached prefix. Quick tier, CPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scaletorch_tpu.inference import InferenceEngine, SamplingParams
from scaletorch_tpu.models import llama

TINY = dict(
    vocab_size=64, hidden_size=32, intermediate_size=64,
    num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
    dtype=jnp.float32,
)
GREEDY = SamplingParams(temperature=0.0)
SYS = [7, 7, 7, 7, 3, 3, 3, 3]  # two full pages at page_size=4


@pytest.fixture(scope="module")
def tiny_llama():
    cfg = llama.LlamaConfig(**TINY)
    return cfg, llama.init_params(jax.random.PRNGKey(0), cfg)


def make_engine(params, cfg, **kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("max_seq", 32)
    kw.setdefault("prefill_len", 12)
    kw.setdefault("sampling", GREEDY)
    kw.setdefault("cache_layout", "paged")
    kw.setdefault("page_size", 4)
    return InferenceEngine(params, cfg, **kw)


def warmed_donor(params, cfg):
    """A donor that served one request over SYS, registering its two
    prompt pages in the radix tree."""
    eng = make_engine(params, cfg)
    eng.submit(SYS + [1], max_new_tokens=4)
    eng.run()
    return eng


def export_all(donor):
    pmap = donor.export_prefix_map()
    pages = [p for chain in pmap["chains"] for p in chain["pages"]]
    _meta, contents = donor.export_prefix_pages(pages)
    chains = [(c["tokens"], c["pages"]) for c in pmap["chains"]]
    return pmap, chains, contents


class TestExport:
    def test_prefix_map_shape(self, tiny_llama):
        cfg, params = tiny_llama
        donor = warmed_donor(params, cfg)
        pmap = donor.export_prefix_map()
        assert pmap["page_size"] == 4
        assert pmap["dtype"] == str(donor.cache.k.dtype)
        chain = pmap["chains"][0]
        assert chain["tokens"] == SYS  # the full-page prefix only
        assert len(chain["pages"]) == 2
        for p in chain["pages"]:
            assert pmap["pages"][p]["frozen"] is True
        expected = tuple([donor.cache.k.shape[0]]
                         + list(donor.cache.k.shape[2:]))
        assert tuple(pmap["page_shape"]) == expected

    def test_export_leaves_donor_refcounts_untouched(self, tiny_llama):
        cfg, params = tiny_llama
        donor = warmed_donor(params, cfg)
        pmap = donor.export_prefix_map()
        pages = pmap["chains"][0]["pages"]
        before = {p: donor.allocator.refcount(p) for p in pages}
        _meta, contents = donor.export_prefix_pages(pages + [999])
        assert set(contents) == set(pages)  # unknown page: absent
        after = {p: donor.allocator.refcount(p) for p in pages}
        assert before == after
        donor.allocator.check_conservation()
        # the copy is the real page bytes
        nbytes = int(np.prod([donor.cache.k.shape[0]]
                             + list(donor.cache.k.shape[2:]))
                     * donor.cache.k.dtype.itemsize)
        for k_bytes, v_bytes in contents.values():
            assert len(k_bytes) == nbytes and len(v_bytes) == nbytes

    def test_dense_engine_has_no_map(self, tiny_llama):
        cfg, params = tiny_llama
        eng = make_engine(params, cfg, cache_layout="dense")
        pmap = eng.export_prefix_map()
        assert pmap["chains"] == [] and pmap["pages"] == {}


class TestImportParity:
    def test_warmed_recipient_first_request_hits_and_matches(
            self, tiny_llama):
        """The tentpole attestation: import -> first shared-prefix
        request is a physical prefix hit with bit-identical output and
        no retrace on either end."""
        cfg, params = tiny_llama
        donor = warmed_donor(params, cfg)
        pmap, chains, contents = export_all(donor)

        recipient = make_engine(params, cfg)
        result = recipient.import_prefix_pages(
            chains, contents, dtype=pmap["dtype"],
            page_shape=pmap["page_shape"], page_size=pmap["page_size"])
        assert result["pages"] == 2
        assert result["chains"] == [SYS]
        snap = recipient.metrics.snapshot()
        assert snap["warm_pages_total"] == 2
        assert snap["prefix_pages"] == 2

        # FIRST recipient request rides the warmed pages
        rid = recipient.submit(SYS + [2], max_new_tokens=4)
        recipient.step()  # admission tick
        assert recipient.metrics.prefix_hits == 1
        assert recipient.metrics.prefill_tokens_saved == len(SYS)
        results = recipient.run()

        # bit parity against the donor serving the same request
        rid_d = donor.submit(SYS + [2], max_new_tokens=4)
        donor_results = donor.run()
        assert results[rid].tokens == donor_results[rid_d].tokens
        assert results[rid].outcome == "ok"

        # no retrace through export, import, or the warmed serve
        assert donor.decode_compile_count == 1
        assert recipient.decode_compile_count == 1
        recipient.allocator.check_conservation()
        donor.allocator.check_conservation()

    def test_warmed_pages_are_evictable_at_zero(self, tiny_llama):
        cfg, params = tiny_llama
        donor = warmed_donor(params, cfg)
        pmap, chains, contents = export_all(donor)
        recipient = make_engine(params, cfg)
        recipient.import_prefix_pages(
            chains, contents, dtype=pmap["dtype"],
            page_shape=pmap["page_shape"], page_size=pmap["page_size"])
        # the tree holds the ONLY reference: evicting it all returns
        # the pool to capacity (frozen-from-birth, evictable at zero)
        recipient.radix.evict(recipient.num_pages)
        assert recipient.allocator.free_count == \
            recipient.allocator.capacity
        recipient.allocator.check_conservation()

    def test_import_dedups_shared_donor_pages(self, tiny_llama):
        """Two chains sharing a donor page import it ONCE."""
        cfg, params = tiny_llama
        donor = make_engine(params, cfg)
        donor.submit(SYS + [1], max_new_tokens=4)
        donor.run()
        donor.submit(SYS[:4] + [9, 9, 9, 9, 2], max_new_tokens=4)
        donor.run()
        pmap, chains, contents = export_all(donor)
        assert len(chains) == 2  # shared first page, diverging second
        recipient = make_engine(params, cfg)
        result = recipient.import_prefix_pages(
            chains, contents, dtype=pmap["dtype"],
            page_shape=pmap["page_shape"], page_size=pmap["page_size"])
        assert result["pages"] == 3  # 2 + 2 chains, 1 shared page
        recipient.allocator.check_conservation()
        # both warmed chains are servable, still on one compile
        recipient.submit(SYS + [3], max_new_tokens=2)
        recipient.submit(SYS[:4] + [9, 9, 9, 9, 3], max_new_tokens=2)
        recipient.run()
        assert recipient.metrics.prefix_hits == 2
        assert donor.decode_compile_count == 1
        assert recipient.decode_compile_count == 1


class TestImportDegradation:
    def test_partial_contents_keep_valid_prefix(self, tiny_llama):
        """A dropped chunk sheds the chain's TAIL only — conservation
        holds on the recipient and the surviving prefix still hits."""
        cfg, params = tiny_llama
        donor = warmed_donor(params, cfg)
        pmap, chains, contents = export_all(donor)
        second_page = chains[0][1][1]
        del contents[second_page]  # the chunk that never arrived
        recipient = make_engine(params, cfg)
        result = recipient.import_prefix_pages(
            chains, contents, dtype=pmap["dtype"],
            page_shape=pmap["page_shape"], page_size=pmap["page_size"])
        assert result["pages"] == 1
        assert result["chains"] == [SYS[:4]]
        recipient.allocator.check_conservation()
        recipient.submit(SYS + [2], max_new_tokens=4)
        recipient.step()
        assert recipient.metrics.prefill_tokens_saved == 4
        recipient.run()
        recipient.allocator.check_conservation()
        assert recipient.decode_compile_count == 1

    def test_aborted_import_releases_every_allocation(self, tiny_llama):
        """An exception mid-import (the transfer interrupted between
        write and registration) must leave the allocator exactly where
        it started — the conservation oracle stays green."""
        cfg, params = tiny_llama
        donor = warmed_donor(params, cfg)
        pmap, chains, contents = export_all(donor)
        recipient = make_engine(params, cfg)
        free_before = recipient.allocator.free_count

        def boom(tokens, pages):
            raise RuntimeError("interrupted mid-registration")

        recipient.radix.insert = boom
        with pytest.raises(RuntimeError):
            recipient.import_prefix_pages(
                chains, contents, dtype=pmap["dtype"],
                page_shape=pmap["page_shape"],
                page_size=pmap["page_size"])
        recipient.allocator.check_conservation()
        assert recipient.allocator.free_count == free_before
        assert recipient.metrics.warm_pages_total == 0

    def test_incompatible_pool_is_refused(self, tiny_llama):
        cfg, params = tiny_llama
        donor = warmed_donor(params, cfg)
        pmap, chains, contents = export_all(donor)
        recipient = make_engine(params, cfg, page_size=8)
        result = recipient.import_prefix_pages(
            chains, contents, dtype=pmap["dtype"],
            page_shape=pmap["page_shape"], page_size=pmap["page_size"])
        assert result == {"pages": 0, "chains": []}
        recipient.allocator.check_conservation()
        assert recipient.allocator.free_count == \
            recipient.allocator.capacity

    def test_pool_pressure_warms_what_fits(self, tiny_llama):
        """Allocator exhaustion mid-import keeps what was allocated
        (a valid prefix), sheds the rest, and conserves."""
        cfg, params = tiny_llama
        donor = warmed_donor(params, cfg)
        pmap, chains, contents = export_all(donor)
        # 2 pool pages, one reserved: exactly ONE allocatable page
        recipient = make_engine(params, cfg, num_pages=2)
        result = recipient.import_prefix_pages(
            chains, contents, dtype=pmap["dtype"],
            page_shape=pmap["page_shape"], page_size=pmap["page_size"])
        assert result["pages"] == 1  # one page fit; the tail shed
        recipient.allocator.check_conservation()
