"""Building-block numerics: RMSNorm, RoPE, SDPA, losses."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scaletorch_tpu.models.layers import (
    apply_rotary_pos_emb,
    cross_entropy_loss,
    get_cos_sin,
    repeat_kv,
    rms_norm,
    sdpa_attention,
    sdpa_attention_with_lse,
)


class TestRmsNorm:
    def test_matches_manual(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 5, 8))
        w = jnp.linspace(0.5, 1.5, 8)
        out = rms_norm(x, w, eps=1e-6)
        expected = x / np.sqrt((np.asarray(x) ** 2).mean(-1, keepdims=True) + 1e-6) * w
        np.testing.assert_allclose(out, expected, rtol=1e-5)

    def test_preserves_dtype_fp32_internal(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 8)).astype(jnp.bfloat16)
        out = rms_norm(x, jnp.ones(8))
        assert out.dtype == jnp.bfloat16

    @pytest.mark.slow
    def test_memory_lean_vjp_matches_autodiff(self):
        """The custom VJP (saves original-dtype x/w, recomputes fp32
        internals) must agree with plain autodiff of the same math."""

        def ref(x, w, eps=1e-6):
            x32 = x.astype(jnp.float32)
            v = jnp.mean(x32 * x32, axis=-1, keepdims=True)
            return (x32 * jax.lax.rsqrt(v + eps) * w).astype(x.dtype)

        # Layer-norm shape ([B,S,H] vs [H]) and per-head qk-norm shape
        # ([B,S,Hq,Dh] vs [Dh]) exercise both dw broadcast-reduction paths.
        for shape, wshape in (((2, 5, 8), (8,)), ((2, 5, 4, 8), (8,))):
            x = jax.random.normal(jax.random.PRNGKey(0), shape)
            w = jax.random.normal(jax.random.PRNGKey(1), wshape) + 1.0
            loss = lambda f: lambda a, b: jnp.sum(jnp.sin(f(a, b)))  # noqa: E731
            gx, gw = jax.grad(loss(rms_norm), argnums=(0, 1))(x, w)
            rx, rw = jax.grad(loss(ref), argnums=(0, 1))(x, w)
            np.testing.assert_allclose(gx, rx, rtol=1e-5, atol=1e-6)
            np.testing.assert_allclose(gw, rw, rtol=1e-5, atol=1e-6)


class TestSwiglu:
    def test_forward_and_vjp_match_autodiff(self):
        from scaletorch_tpu.models.layers import swiglu

        g = jax.random.normal(jax.random.PRNGKey(2), (2, 16, 32))
        u = jax.random.normal(jax.random.PRNGKey(3), (2, 16, 32))
        np.testing.assert_allclose(
            swiglu(g, u), jax.nn.silu(g) * u, rtol=1e-6)
        s1 = jax.grad(lambda a, b: jnp.sum(swiglu(a, b) ** 2), argnums=(0, 1))(g, u)
        s2 = jax.grad(
            lambda a, b: jnp.sum((jax.nn.silu(a) * b) ** 2), argnums=(0, 1))(g, u)
        for got, want in zip(s1, s2):
            np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_bf16_dtype_preserved(self):
        from scaletorch_tpu.models.layers import swiglu

        g = jax.random.normal(jax.random.PRNGKey(4), (4, 8), jnp.bfloat16)
        u = jax.random.normal(jax.random.PRNGKey(5), (4, 8), jnp.bfloat16)
        out, vjp = jax.vjp(swiglu, g, u)
        assert out.dtype == jnp.bfloat16
        dg, du = vjp(jnp.ones_like(out))
        assert dg.dtype == jnp.bfloat16 and du.dtype == jnp.bfloat16


class TestRope:
    def test_rotation_preserves_norm(self):
        q = jax.random.normal(jax.random.PRNGKey(1), (1, 2, 16, 8))
        cos, sin = get_cos_sin(16, 8)
        q_rot, _ = apply_rotary_pos_emb(q, q, cos, sin)
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(q_rot), axis=-1),
            np.linalg.norm(np.asarray(q), axis=-1),
            rtol=1e-5,
        )

    def test_relative_position_property(self):
        """<rope(q,m), rope(k,n)> depends only on m-n."""
        d = 8
        q = jax.random.normal(jax.random.PRNGKey(2), (1, 1, 1, d))
        k = jax.random.normal(jax.random.PRNGKey(3), (1, 1, 1, d))

        def dot_at(m, n):
            cos_m, sin_m = get_cos_sin(1, d, positions=jnp.array([m]))
            cos_n, sin_n = get_cos_sin(1, d, positions=jnp.array([n]))
            qm, _ = apply_rotary_pos_emb(q, q, cos_m, sin_m)
            kn, _ = apply_rotary_pos_emb(k, k, cos_n, sin_n)
            return float(jnp.sum(qm * kn))

        assert dot_at(5, 3) == pytest.approx(dot_at(12, 10), rel=1e-4)
        assert dot_at(5, 3) != pytest.approx(dot_at(5, 4), rel=1e-2)

    def test_positions_override_slices_table(self):
        """CP parity: rank-local positions give rows of the global table
        (reference update_rope_for_context_parallel)."""
        cos_full, sin_full = get_cos_sin(16, 8)
        cos_shard, sin_shard = get_cos_sin(
            8, 8, positions=jnp.arange(8, 16)
        )
        np.testing.assert_allclose(cos_shard, cos_full[8:], rtol=1e-6)
        np.testing.assert_allclose(sin_shard, sin_full[8:], rtol=1e-6)


class TestRepeatKv:
    def test_expand(self):
        k = jnp.arange(2 * 2 * 3 * 4.0).reshape(2, 2, 3, 4)
        out = repeat_kv(k, 3)
        assert out.shape == (2, 6, 3, 4)
        np.testing.assert_array_equal(out[:, 0], out[:, 1])
        np.testing.assert_array_equal(out[:, 0], k[:, 0])
        np.testing.assert_array_equal(out[:, 3], k[:, 1])

    def test_noop(self):
        k = jnp.ones((1, 2, 3, 4))
        assert repeat_kv(k, 1) is k


class TestSdpa:
    def test_causal_masking(self):
        """Output at position i must not depend on keys > i."""
        key = jax.random.PRNGKey(4)
        q, k, v = (jax.random.normal(kk, (1, 2, 6, 8)) for kk in jax.random.split(key, 3))
        out1 = sdpa_attention(q, k, v, causal=True)
        # perturb the last key/value: only the last position may change
        k2 = k.at[:, :, -1].add(10.0)
        v2 = v.at[:, :, -1].add(10.0)
        out2 = sdpa_attention(q, k2, v2, causal=True)
        np.testing.assert_allclose(out1[:, :, :-1], out2[:, :, :-1], atol=1e-6)
        assert not np.allclose(out1[:, :, -1], out2[:, :, -1])

    def test_matches_naive_loop(self):
        key = jax.random.PRNGKey(5)
        q, k, v = (jax.random.normal(kk, (1, 1, 4, 4)) for kk in jax.random.split(key, 3))
        out = np.asarray(sdpa_attention(q, k, v, causal=True))[0, 0]
        qn, kn, vn = np.asarray(q)[0, 0], np.asarray(k)[0, 0], np.asarray(v)[0, 0]
        for i in range(4):
            scores = (qn[i] @ kn[: i + 1].T) / np.sqrt(4)
            p = np.exp(scores - scores.max())
            p /= p.sum()
            np.testing.assert_allclose(out[i], p @ vn[: i + 1], rtol=1e-5, atol=1e-6)

    @pytest.mark.slow
    def test_gqa_matches_expanded(self):
        key = jax.random.PRNGKey(6)
        q = jax.random.normal(key, (2, 4, 5, 8))
        k = jax.random.normal(jax.random.fold_in(key, 1), (2, 2, 5, 8))
        v = jax.random.normal(jax.random.fold_in(key, 2), (2, 2, 5, 8))
        out = sdpa_attention(q, k, v, causal=True)
        out_exp = sdpa_attention(q, repeat_kv(k, 2), repeat_kv(v, 2), causal=True)
        np.testing.assert_allclose(out, out_exp, atol=1e-6)

    def test_lse_variant_consistent(self):
        key = jax.random.PRNGKey(7)
        q, k, v = (jax.random.normal(kk, (1, 2, 6, 8)) for kk in jax.random.split(key, 3))
        out_ref = sdpa_attention(q, k, v, causal=True)
        out, lse = sdpa_attention_with_lse(q, k, v, causal=True)
        np.testing.assert_allclose(out, out_ref, atol=1e-5)
        assert lse.shape == (1, 2, 6)
        assert lse.dtype == jnp.float32


class TestCrossEntropy:
    def test_matches_manual(self):
        logits = jax.random.normal(jax.random.PRNGKey(8), (2, 3, 5))
        targets = jnp.array([[0, 1, 2], [3, 4, 0]])
        loss = cross_entropy_loss(logits, targets)
        logp = jax.nn.log_softmax(np.asarray(logits, dtype=np.float32), axis=-1)
        expected = -np.take_along_axis(
            np.asarray(logp), np.asarray(targets)[..., None], axis=-1
        ).mean()
        assert float(loss) == pytest.approx(float(expected), rel=1e-5)

    def test_ignore_index(self):
        logits = jax.random.normal(jax.random.PRNGKey(9), (1, 4, 5))
        t_full = jnp.array([[1, 2, 3, 4]])
        t_masked = jnp.array([[1, 2, -100, -100]])
        l_masked = cross_entropy_loss(logits, t_masked)
        l_first_two = cross_entropy_loss(logits[:, :2], t_full[:, :2])
        assert float(l_masked) == pytest.approx(float(l_first_two), rel=1e-5)

    def test_all_ignored_is_finite(self):
        logits = jnp.ones((1, 2, 5))
        loss = cross_entropy_loss(logits, jnp.full((1, 2), -100))
        assert float(loss) == 0.0
