"""Llama/Qwen3 model-level tests: shapes, param counts, variants."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scaletorch_tpu.models.llama import Llama, LlamaConfig, forward, init_params
from scaletorch_tpu.models.qwen3 import Qwen3Config
from scaletorch_tpu.utils.misc import get_num_params

TINY = dict(
    vocab_size=64,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def tiny():
    cfg = LlamaConfig(**TINY)
    params = init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


class TestLlama:
    def test_forward_shape(self, tiny):
        cfg, params = tiny
        ids = jnp.zeros((2, 8), jnp.int32)
        logits = forward(params, ids, cfg)
        assert logits.shape == (2, 8, cfg.vocab_size)

    def test_analytic_param_count(self, tiny):
        cfg, params = tiny
        assert get_num_params(params) == cfg.num_params()

    def test_qwen3_param_count_and_shape(self):
        cfg = Qwen3Config(**{**TINY, "head_dim": 16})
        params = init_params(jax.random.PRNGKey(0), cfg)
        assert get_num_params(params) == cfg.num_params()
        assert "q_norm" in params["layers"]
        assert "lm_head" not in params  # tied
        logits = forward(params, jnp.zeros((1, 4), jnp.int32), cfg)
        assert logits.shape == (1, 4, cfg.vocab_size)

    def test_explicit_head_dim(self):
        """Qwen3's head_dim is decoupled from hidden//heads
        (reference model_qwen3.py:148)."""
        cfg = Qwen3Config(**{**TINY, "head_dim": 16})
        assert cfg.actual_head_dim == 16 != cfg.hidden_size // cfg.num_attention_heads
        params = init_params(jax.random.PRNGKey(0), cfg)
        assert params["layers"]["q_proj"].shape == (2, 32, 4 * 16)

    def test_gradient_checkpointing_same_output(self, tiny):
        cfg, params = tiny
        ids = jnp.arange(16, dtype=jnp.int32).reshape(2, 8)
        a = forward(params, ids, cfg, gradient_checkpointing=False)
        b = forward(params, ids, cfg, gradient_checkpointing=True)
        np.testing.assert_allclose(a, b, atol=1e-6)

    @pytest.mark.slow
    def test_gradient_checkpointing_same_grads(self, tiny):
        cfg, params = tiny
        ids = jnp.arange(16, dtype=jnp.int32).reshape(2, 8)

        def loss(p, gc):
            return forward(p, ids, cfg, gradient_checkpointing=gc).sum()

        g_a = jax.grad(lambda p: loss(p, False))(params)
        g_b = jax.grad(lambda p: loss(p, True))(params)
        for a, b in zip(jax.tree.leaves(g_a), jax.tree.leaves(g_b)):
            # recompute-under-checkpoint may fuse differently; allow small
            # relative drift on the large sum-loss gradients
            np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-4)

    def test_causality_end_to_end(self, tiny):
        cfg, params = tiny
        ids = jnp.arange(16, dtype=jnp.int32).reshape(2, 8)
        base = forward(params, ids, cfg)
        ids2 = ids.at[:, -1].set(0)
        pert = forward(params, ids2, cfg)
        np.testing.assert_allclose(base[:, :-1], pert[:, :-1], atol=1e-6)

    def test_positions_override(self, tiny):
        """Positions shift the output (RoPE) — the CP hook."""
        cfg, params = tiny
        ids = jnp.arange(8, dtype=jnp.int32).reshape(1, 8)
        a = forward(params, ids, cfg)
        b = forward(params, ids, cfg, positions=jnp.arange(8, 16))
        assert not np.allclose(a, b)

    def test_oo_veneer(self, tiny):
        cfg, _ = tiny
        model = Llama(cfg)
        params = model.init(jax.random.PRNGKey(1))
        out = model(params, jnp.zeros((1, 4), jnp.int32))
        assert out.shape == (1, 4, cfg.vocab_size)

    def test_from_hf_config(self):
        class FakeHf:
            vocab_size = 128
            hidden_size = 64
            intermediate_size = 128
            num_hidden_layers = 3
            num_attention_heads = 8
            num_key_value_heads = 4
            max_position_embeddings = 512
            rope_theta = 5e5
            rms_norm_eps = 1e-5
            tie_word_embeddings = True

        cfg = LlamaConfig.from_hf(FakeHf())
        assert cfg.num_hidden_layers == 3
        assert cfg.rope_theta == 5e5
        assert cfg.tie_word_embeddings
