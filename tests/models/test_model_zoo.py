"""Model-zoo parity: GPT-MoE, LeNet, attention variants.

Mirrors reference tests/models/test_moe_model.py (routing + forward
shapes) and the attention-variant surface (models/attention/).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scaletorch_tpu.models.attention import (
    AttentionConfig,
    GroupQueryAttention,
    MultiHeadAttention,
    MultiHeadLatentAttention,
    MultiQueryAttention,
)
from scaletorch_tpu.models.gpt_moe import (
    GPTMoE,
    GPTMoEConfig,
    estimate_mfu,
    generate,
)
from scaletorch_tpu.models.lenet import LeNet, LeNetConfig

MOE_CFG = GPTMoEConfig(
    block_size=32, vocab_size=65, n_layer=2, n_head=4, n_embd=64,
    num_experts=4, top_k=2, capacity_factor=4.0,
)


class TestGPTMoE:
    @pytest.fixture(scope="class")
    def setup(self):
        model = GPTMoE(MOE_CFG)
        params = model.init(jax.random.PRNGKey(0))
        ids = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 65)
        return model, params, ids

    def test_forward_shapes_and_aux(self, setup):
        model, params, ids = setup
        logits, aux = model(params, ids, return_aux=True)
        assert logits.shape == (2, 16, 65)
        assert np.isfinite(float(aux)) and float(aux) > 0
        assert np.all(np.isfinite(np.asarray(logits)))

    @pytest.mark.slow
    def test_noisy_routing_changes_logits(self, setup):
        model, params, ids = setup
        det = model(params, ids)
        noisy = model(params, ids, noise_key=jax.random.PRNGKey(2))
        assert not np.allclose(np.asarray(det), np.asarray(noisy))
        # deterministic path is reproducible
        np.testing.assert_array_equal(model(params, ids), det)

    def test_dense_variant(self):
        cfg = GPTMoEConfig(
            block_size=32, vocab_size=65, n_layer=2, n_head=4, n_embd=64,
            use_moe=False,
        )
        model = GPTMoE(cfg)
        params = model.init(jax.random.PRNGKey(0))
        assert "mlp_fc" in params["layers"]
        logits = model(params, jnp.zeros((1, 8), jnp.int32))
        assert logits.shape == (1, 8, 65)

    def test_generate_greedy_deterministic(self, setup):
        model, params, _ = setup
        prompt = jnp.array([[1, 2, 3]], dtype=jnp.int32)
        out1 = generate(params, prompt, MOE_CFG, max_new_tokens=5,
                        temperature=0.0)
        out2 = generate(params, prompt, MOE_CFG, max_new_tokens=5,
                        temperature=0.0)
        assert out1.shape == (1, 8)
        np.testing.assert_array_equal(out1, out2)
        np.testing.assert_array_equal(out1[:, :3], prompt)  # prompt intact

    def test_generate_sampling(self, setup):
        model, params, _ = setup
        prompt = jnp.array([[1, 2, 3]], dtype=jnp.int32)
        out = generate(params, prompt, MOE_CFG, max_new_tokens=4,
                       temperature=1.0, key=jax.random.PRNGKey(7))
        assert out.shape == (1, 7)
        assert bool(jnp.all((out >= 0) & (out < 65)))

    def test_estimate_mfu(self, setup):
        _, params, _ = setup
        mfu = estimate_mfu(MOE_CFG, params, tokens_per_second=1e4,
                           peak_flops=197e12)
        assert 0 < mfu < 1


class TestLeNet:
    def test_forward(self):
        model = LeNet(LeNetConfig())
        params = model.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 28, 28, 1))
        logits = model(params, x)
        assert logits.shape == (4, 10)
        assert np.all(np.isfinite(np.asarray(logits)))


class TestAttentionVariants:
    CFG = AttentionConfig(embed_dim=64, num_heads=8, num_kv_heads=2,
                          kv_lora_rank=16)

    @pytest.mark.parametrize("cls", [
        MultiHeadAttention, MultiQueryAttention, GroupQueryAttention,
        MultiHeadLatentAttention,
    ])
    def test_shapes(self, cls):
        attn = cls(self.CFG)
        params = attn.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 64))
        y = attn(params, x)
        assert y.shape == x.shape
        assert np.all(np.isfinite(np.asarray(y)))

    def test_gqa_with_all_heads_equals_mha(self):
        cfg = AttentionConfig(embed_dim=64, num_heads=8, num_kv_heads=8)
        mha, gqa = MultiHeadAttention(cfg), GroupQueryAttention(cfg)
        params = mha.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 12, 64))
        np.testing.assert_allclose(
            np.asarray(mha(params, x)), np.asarray(gqa(params, x)), rtol=1e-6
        )

    def test_kv_param_savings(self):
        mha = MultiHeadAttention(self.CFG).init(jax.random.PRNGKey(0))
        mqa = MultiQueryAttention(self.CFG).init(jax.random.PRNGKey(0))
        assert mqa["k_proj"].size == mha["k_proj"].size // 8

    def test_causality(self):
        """Changing a future token must not affect earlier outputs."""
        attn = GroupQueryAttention(self.CFG)
        params = attn.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (1, 8, 64))
        y1 = attn(params, x)
        x2 = x.at[:, -1].set(0.0)
        y2 = attn(params, x2)
        np.testing.assert_allclose(
            np.asarray(y1[:, :-1]), np.asarray(y2[:, :-1]), atol=1e-6
        )

    def test_config_validation(self):
        with pytest.raises(ValueError, match="not divisible"):
            AttentionConfig(embed_dim=65, num_heads=8)
        with pytest.raises(ValueError, match="num_kv_heads"):
            AttentionConfig(embed_dim=64, num_heads=8, num_kv_heads=3)

    def test_mla_with_q_lora(self):
        cfg = AttentionConfig(embed_dim=64, num_heads=8, q_lora_rank=16,
                              kv_lora_rank=16)
        attn = MultiHeadLatentAttention(cfg)
        params = attn.init(jax.random.PRNGKey(0))
        assert "q_down" in params and "q_up" in params
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 64))
        assert attn(params, x).shape == x.shape
