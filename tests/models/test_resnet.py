"""ResNet (models/resnet.py) — architecture parity + BN semantics."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scaletorch_tpu.models.resnet import ResNetConfig, forward, init_params

# Heavyweight end-to-end tier (VERDICT r3 weak #7): full runs, not CI units
pytestmark = pytest.mark.slow


class TestArchitecture:
    def test_param_counts_match_torchvision(self):
        """Exact published torchvision counts: resnet18 11,689,512 /
        resnet34 21,797,672 (1000 classes) — the strongest offline golden
        for architectural parity with the reference's model zoo."""
        assert ResNetConfig(depth=18).num_params() == 11_689_512
        assert ResNetConfig(depth=34).num_params() == 21_797_672

    def test_output_shape_and_downsampling(self):
        cfg = ResNetConfig(depth=18, num_classes=10, width=16, image_size=64)
        p, s = init_params(jax.random.key(0), cfg)
        x = jax.random.normal(jax.random.key(1), (2, 64, 64, 3))
        logits, new_s = forward(p, s, x, cfg, train=True)
        assert logits.shape == (2, 10)
        # state tree mirrors the params' bn layout
        assert jax.tree.structure(new_s) == jax.tree.structure(s)


class TestBatchNorm:
    def test_eval_uses_running_stats(self):
        cfg = ResNetConfig(depth=18, num_classes=4, width=8, image_size=32,
                           bn_momentum=1.0)  # running <- batch in one step
        p, s = init_params(jax.random.key(0), cfg)
        x = jax.random.normal(jax.random.key(1), (8, 32, 32, 3))
        logits_train, s1 = forward(p, s, x, cfg, train=True)
        # with momentum 1.0 the running stats ARE the batch stats, so an
        # eval pass on the same batch must reproduce the train output
        logits_eval, s2 = forward(p, s1, x, cfg, train=False)
        np.testing.assert_allclose(
            np.asarray(logits_train), np.asarray(logits_eval),
            rtol=1e-4, atol=1e-4)
        # eval must NOT advance the stats
        for a, b in zip(jax.tree.leaves(s1), jax.tree.leaves(s2)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_train_updates_running_stats(self):
        cfg = ResNetConfig(depth=18, num_classes=4, width=8, image_size=32)
        p, s = init_params(jax.random.key(0), cfg)
        x = 3.0 + jax.random.normal(jax.random.key(1), (8, 32, 32, 3))
        _, s1 = forward(p, s, x, cfg, train=True)
        moved = [
            float(jnp.abs(b - a).max())
            for a, b in zip(jax.tree.leaves(s), jax.tree.leaves(s1))
        ]
        assert max(moved) > 0
