"""Golden tests for the Pallas flash-attention kernel (interpret mode on
CPU) against the dense sdpa reference — the same strategy the reference
uses for its ring-attention math (reference
tests/parallel/test_context_parallel.py:72-106)."""

import jax
import jax.numpy as jnp
import pytest

from scaletorch_tpu.models.layers import sdpa_attention
from scaletorch_tpu.ops.pallas.flash import pallas_flash_attention


def _qkv(b=2, hq=4, hkv=2, s=256, d=64, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(jax.random.key(0), 3)
    return (
        jax.random.normal(kq, (b, hq, s, d), dtype),
        jax.random.normal(kk, (b, hkv, s, d), dtype),
        jax.random.normal(kv, (b, hkv, s, d), dtype),
    )


@pytest.mark.parametrize("causal", [True, False])
def test_forward_matches_sdpa(causal):
    q, k, v = _qkv()
    out = pallas_flash_attention(
        q, k, v, causal=causal, block_q=128, block_kv=128, interpret=True
    )
    ref = sdpa_attention(q, k, v, causal=causal)
    assert jnp.max(jnp.abs(out - ref)) < 1e-5


@pytest.mark.slow
@pytest.mark.parametrize("causal", [True, False])
def test_gradients_match_sdpa(causal):
    q, k, v = _qkv(s=128, d=32)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

    gp = jax.grad(
        loss(lambda q, k, v: pallas_flash_attention(
            q, k, v, causal=causal, block_q=64, block_kv=64, interpret=True
        )),
        argnums=(0, 1, 2),
    )(q, k, v)
    gr = jax.grad(
        loss(lambda q, k, v: sdpa_attention(q, k, v, causal=causal)),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(gp, gr):
        assert jnp.max(jnp.abs(a - b)) < 1e-4


def test_mqa_single_kv_head():
    q, k, v = _qkv(hq=4, hkv=1, s=128, d=32)
    out = pallas_flash_attention(
        q, k, v, causal=True, block_q=64, block_kv=64, interpret=True
    )
    ref = sdpa_attention(q, k, v, causal=True)
    assert jnp.max(jnp.abs(out - ref)) < 1e-5


@pytest.mark.slow
@pytest.mark.parametrize("bq,bkv", [(64, 32), (32, 64)])
def test_mismatched_block_sizes_causal(bq, bkv):
    # regression: the causal DMA clamp must convert between query- and
    # key-block units, not compare raw block indices
    q, k, v = _qkv(s=128, d=32)
    out = pallas_flash_attention(
        q, k, v, causal=True, block_q=bq, block_kv=bkv, interpret=True
    )
    ref = sdpa_attention(q, k, v, causal=True)
    assert jnp.max(jnp.abs(out - ref)) < 1e-5

    gp = jax.grad(
        lambda q, k, v: jnp.sum(pallas_flash_attention(
            q, k, v, causal=True, block_q=bq, block_kv=bkv, interpret=True
        ) ** 2),
        argnums=(0, 1, 2),
    )(q, k, v)
    gr = jax.grad(
        lambda q, k, v: jnp.sum(sdpa_attention(q, k, v, causal=True) ** 2),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(gp, gr):
        assert jnp.max(jnp.abs(a - b)) < 1e-4


def test_uneven_block_fallback():
    # seq not divisible by the preferred block: _pick_block halves it
    q, k, v = _qkv(s=192, d=32)
    out = pallas_flash_attention(
        q, k, v, causal=True, block_q=128, block_kv=128, interpret=True
    )
    ref = sdpa_attention(q, k, v, causal=True)
    assert jnp.max(jnp.abs(out - ref)) < 1e-5


def test_flash_jax_rejects_nondivisible_gqa_heads():
    """flash_attention_jax mirrors the in-repo entry points' explicit
    guard: hq % hkv != 0 must raise up front instead of floor-dividing
    into an obscure head-count mismatch inside jax's kernel."""
    from scaletorch_tpu.ops.flash_attention import flash_attention_jax

    q, k, v = _qkv(hq=4, hkv=3, s=64, d=32)
    with pytest.raises(ValueError, match="multiple of key/value heads"):
        flash_attention_jax(q, k, v)
