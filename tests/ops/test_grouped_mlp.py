"""Grouped-MLP Pallas kernel: slot-skipping expert compute.

The npu_grouped_matmul-role kernel (reference models/npu_patch.py:94-131)
is validated in interpret mode against the masked dense reference, and
end-to-end: a Qwen3-MoE forward with the kernel toggled on must produce
bit-comparable outputs to the batched-einsum path — the kernel only
skips slots that are zero anyway.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from scaletorch_tpu.ops.pallas.grouped_mlp import (
    masked_grouped_mlp,
    grouped_swiglu_mlp,
    slot_fill_counts,
)


def _problem(seed=0, e=4, g=2, c=8, h=16, i=32):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((e, g, c, h)).astype(np.float32)
    counts = rng.integers(0, c + 1, size=(e, g)).astype(np.int32)
    wg = (rng.standard_normal((e, h, i)) * 0.1).astype(np.float32)
    wu = (rng.standard_normal((e, h, i)) * 0.1).astype(np.float32)
    wd = (rng.standard_normal((e, i, h)) * 0.1).astype(np.float32)
    return tuple(map(jnp.asarray, (x, counts, wg, wu, wd)))


class TestKernelParity:
    def test_forward_matches_masked_dense(self):
        x, counts, wg, wu, wd = _problem()
        out = grouped_swiglu_mlp(x, counts, wg, wu, wd, 4, 16, True)
        ref = masked_grouped_mlp(x, counts, wg, wu, wd)
        np.testing.assert_allclose(out, ref, atol=1e-5)
        # rows past the fill count are structurally zero
        assert float(jnp.abs(out[0, 0, int(counts[0, 0]):]).max()) == 0.0

    @pytest.mark.slow
    def test_vjp_matches_masked_dense(self):
        x, counts, wg, wu, wd = _problem()

        def loss(x, wg, wu, wd):
            return jnp.sum(
                grouped_swiglu_mlp(x, counts, wg, wu, wd, 4, 16, True) ** 2)

        def loss_ref(x, wg, wu, wd):
            return jnp.sum(masked_grouped_mlp(x, counts, wg, wu, wd) ** 2)

        g = jax.grad(loss, argnums=(0, 1, 2, 3))(x, wg, wu, wd)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(x, wg, wu, wd)
        for a, b in zip(g, gr):
            np.testing.assert_allclose(a, b, atol=1e-5)

    def test_vjp_masks_cotangents_past_fill_count(self):
        """The ``loss = sum(out**2)`` probe above has zero cotangent on
        padded rows by construction; feed a DENSE random cotangent so the
        dW kernel's do-masking is actually exercised — upstream gradients
        of structurally-zero outputs must not train the weights."""
        x, counts, wg, wu, wd = _problem(seed=3)
        do = jnp.asarray(
            np.random.default_rng(9).standard_normal(x.shape).astype(np.float32))

        def loss(x, wg, wu, wd):
            return jnp.sum(
                grouped_swiglu_mlp(x, counts, wg, wu, wd, 4, 16, True) * do)

        def loss_ref(x, wg, wu, wd):
            return jnp.sum(masked_grouped_mlp(x, counts, wg, wu, wd) * do)

        g = jax.grad(loss, argnums=(0, 1, 2, 3))(x, wg, wu, wd)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(x, wg, wu, wd)
        for a, b in zip(g, gr):
            np.testing.assert_allclose(a, b, atol=1e-5)

    def test_slot_fill_counts(self):
        # [G, N, E, C] one-hots: fill counts are per-(e, g) occupancies
        disp = np.zeros((2, 4, 3, 2), np.float32)
        disp[0, 0, 1, 0] = 1
        disp[0, 2, 1, 1] = 1
        disp[1, 3, 2, 0] = 1
        counts = slot_fill_counts(jnp.asarray(disp))
        assert counts.shape == (3, 2)
        assert counts[1, 0] == 2 and counts[2, 1] == 1 and counts[0, 0] == 0


class TestMoEForwardToggle:
    def test_env_is_config_default_at_construction(self, monkeypatch):
        from scaletorch_tpu.models.qwen3_moe import Qwen3MoEConfig

        monkeypatch.setenv("SCALETORCH_TPU_GROUPED_MLP_KERNEL", "1")
        assert Qwen3MoEConfig().use_grouped_mlp_kernel is True
        monkeypatch.setenv("SCALETORCH_TPU_GROUPED_MLP_KERNEL", "0")
        assert Qwen3MoEConfig().use_grouped_mlp_kernel is False
        # post-construction env flips don't reach an existing config
        cfg = Qwen3MoEConfig()
        monkeypatch.setenv("SCALETORCH_TPU_GROUPED_MLP_KERNEL", "1")
        assert cfg.use_grouped_mlp_kernel is False

    @pytest.mark.slow
    @pytest.mark.parametrize("dispatch", ["einsum", "index"])
    @pytest.mark.parametrize("ep", [1, 2])
    def test_kernel_path_matches_einsum_path(self, ep, dispatch):
        """Kernel on/off parity under BOTH dispatch modes — 'index' is the
        combination the flagship E=128 config auto-selects, where the
        kernel's fill counts come from slot_fill_counts_indexed."""
        from scaletorch_tpu.models.qwen3_moe import (
            Qwen3MoEConfig,
            forward,
            init_params,
            qwen3_moe_param_specs,
        )
        from scaletorch_tpu.parallel.mesh import MeshManager

        cfg = Qwen3MoEConfig(
            vocab_size=64, hidden_size=32, intermediate_size=64,
            moe_intermediate_size=48, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=4, head_dim=8,
            num_experts=4, num_experts_per_tok=2, capacity_factor=1.25,
            dtype=jnp.float32, qk_norm=True, tie_word_embeddings=False,
            moe_dispatch=dispatch,
        )
        params = init_params(jax.random.PRNGKey(0), cfg)
        ids = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 64)

        outs = {}
        for mode in ("plain", "kernel"):
            # the toggle is a CONFIG field (resolved from the env once at
            # construction) so two settings can trace in one process
            mcfg = dataclasses.replace(
                cfg, use_grouped_mlp_kernel=(mode == "kernel"))
            if ep == 1:
                outs[mode] = forward(params, ids, mcfg)
            else:
                mm = MeshManager(ep=ep, dp=8 // ep)
                specs = qwen3_moe_param_specs(cfg, tp_axis="tp", ep_axis="ep")

                def f(p, i):
                    out = forward(p, i, mcfg, ep_axis="ep")
                    # logits vary over (ep, tp) via the expert shards'
                    # spec; collapse the identical copies
                    return jax.lax.pmean(out, ("ep", "tp"))

                outs[mode] = jax.shard_map(
                    f, mesh=mm.mesh, in_specs=(specs, P()), out_specs=P(),
                )(params, ids)
        np.testing.assert_allclose(outs["kernel"], outs["plain"],
                                   atol=2e-5)
