"""Quantized gradient all-reduce: numerics, determinism, wire bytes.

Three contracts attested here (ISSUE 6 acceptance):
  * block round-trip error is bounded by half a quantization step;
  * a REAL train step's int8-reduced gradients match fp32 to cosine
    >= 0.999, its short-run loss curve matches within tolerance, and no
    update is skipped;
  * the compiled HLO moves >= ~3x fewer collective wire bytes on the dp
    axis than the fp32 step (the point of the whole exercise).

All collectives run for real on the 8 virtual CPU devices (conftest.py),
never mocked.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from scaletorch_tpu.ops.quantized_collectives import (
    collective_wire_bytes,
    dequantize_blockwise,
    quantize_blockwise,
    quantized_pmean,
    quantized_pmean_tree,
)
from scaletorch_tpu.parallel.mesh import MeshManager

BLOCK = 64


class TestBlockQuantization:
    def test_roundtrip_error_bound(self):
        rng = np.random.default_rng(0)
        # mix of scales per block, incl. huge + tiny magnitudes
        x = jnp.asarray(
            rng.standard_normal(16 * BLOCK)
            * np.repeat(10.0 ** rng.integers(-4, 4, 16), BLOCK),
            jnp.float32,
        )
        q, s = quantize_blockwise(x, BLOCK)
        assert q.dtype == jnp.int8 and s.dtype == jnp.float32
        err = np.abs(np.asarray(dequantize_blockwise(q, s) - x))
        bound = np.repeat(np.asarray(s), BLOCK) * 0.5
        assert np.all(err <= bound + 1e-12)

    def test_zero_block_safe(self):
        x = jnp.zeros(2 * BLOCK, jnp.float32)
        q, s = quantize_blockwise(x, BLOCK)
        assert np.all(np.asarray(dequantize_blockwise(q, s)) == 0.0)
        assert np.all(np.isfinite(np.asarray(s)))

    def test_unpadded_input_rejected(self):
        with pytest.raises(ValueError, match="multiple of block_size"):
            quantize_blockwise(jnp.zeros(BLOCK + 1, jnp.float32), BLOCK)


def _run_pmean(mm, xs, block=BLOCK):
    """xs: [dp, N] — row r is rank r's local value; returns [dp, N]."""

    def body(v):
        return quantized_pmean(v.reshape(-1), "dp", block_size=block)[None]

    return np.asarray(
        jax.jit(
            jax.shard_map(
                body, mesh=mm.mesh, in_specs=P("dp", None),
                out_specs=P("dp", None),
            )
        )(xs)
    )


class TestQuantizedPmean:
    def test_matches_fp32_mean(self, devices8):
        mm = MeshManager(dp=4, devices=devices8[:4])
        rng = np.random.default_rng(1)
        xs = jnp.asarray(rng.standard_normal((4, 1000)), jnp.float32)
        got = _run_pmean(mm, xs)
        ref = np.mean(np.asarray(xs), axis=0)
        # every rank holds the identical reduced value (the all-gather leg)
        for r in range(1, 4):
            assert np.array_equal(got[0], got[r])
        cos = np.dot(got[0], ref) / (
            np.linalg.norm(got[0]) * np.linalg.norm(ref)
        )
        assert cos >= 0.999
        # elementwise: two quantizations, each bounded by its block scale
        assert np.abs(got[0] - ref).max() < 0.05

    def test_deterministic_across_device_placements(self, devices8):
        """Same logical shards -> bit-identical result no matter which
        physical devices back the dp ranks (the virtual-mesh stand-in for
        'same answer at any host/process layout')."""
        rng = np.random.default_rng(2)
        xs = jnp.asarray(rng.standard_normal((4, 513)), jnp.float32)
        a = _run_pmean(MeshManager(dp=4, devices=devices8[:4]), xs)
        b = _run_pmean(MeshManager(dp=4, devices=devices8[4:][::-1]), xs)
        assert np.array_equal(a, b)

    def test_repeated_runs_bitwise_identical(self, devices8):
        mm = MeshManager(dp=4, devices=devices8[:4])
        rng = np.random.default_rng(3)
        xs = jnp.asarray(rng.standard_normal((4, 257)), jnp.float32)
        assert np.array_equal(_run_pmean(mm, xs), _run_pmean(mm, xs))

    def test_small_leaf_keeps_signal_next_to_large_leaf(self, devices8):
        """Leaves are padded to block boundaries before the fused concat:
        a tiny-magnitude leaf must NOT share an absmax block with a
        large-magnitude neighbor (which would quantize it to zero —
        invisible in aggregate cosine, fatal for that parameter)."""
        mm = MeshManager(dp=4, devices=devices8[:4])
        rng = np.random.default_rng(5)
        tree = {
            "big": jnp.asarray(rng.standard_normal((4, 3 * BLOCK + 7)),
                               jnp.float32),
            "small": jnp.asarray(
                rng.standard_normal((4, BLOCK // 2)) * 1e-4, jnp.float32),
        }

        def body(t):
            local = {k: v[0] for k, v in t.items()}
            out = quantized_pmean_tree(local, "dp", block_size=BLOCK)
            return {k: v[None] for k, v in out.items()}

        got = jax.jit(
            jax.shard_map(
                body, mesh=mm.mesh, in_specs=P("dp"), out_specs=P("dp"),
            )
        )(tree)
        ref = np.mean(np.asarray(tree["small"]), axis=0)
        small = np.asarray(got["small"])[0]
        # relative accuracy appropriate to the SMALL leaf's own scale
        cos = np.dot(small, ref) / (
            np.linalg.norm(small) * np.linalg.norm(ref))
        assert cos >= 0.999, cos
        assert np.abs(small - ref).max() < 1e-5

    def test_tree_fused_matches_per_leaf(self, devices8):
        mm = MeshManager(dp=4, devices=devices8[:4])
        rng = np.random.default_rng(4)
        tree = {
            "w": jnp.asarray(rng.standard_normal((4, 8, 9)), jnp.float32),
            "b": jnp.asarray(rng.standard_normal((4, 33)), jnp.float32),
        }

        def body(t):
            local = {k: v[0] for k, v in t.items()}
            out = quantized_pmean_tree(local, "dp", block_size=BLOCK)
            return {k: v[None] for k, v in out.items()}

        got = jax.jit(
            jax.shard_map(
                body, mesh=mm.mesh, in_specs=P("dp"), out_specs=P("dp"),
            )
        )(tree)
        for k, v in tree.items():
            ref = np.mean(np.asarray(v), axis=0)
            assert np.abs(np.asarray(got[k])[0] - ref).max() < 0.05, k


# ---------------------------------------------------------------------------
# Real-train-step attestation (shared tiny model, compiled once per dtype)
# ---------------------------------------------------------------------------
def _tiny_cfg(dtype, **over):
    from scaletorch_tpu.config import ScaleTorchTPUArguments

    kw = dict(
        model_type="llama", vocab_size=512, hidden_size=64,
        intermediate_size=128, num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=4, head_dim=16, max_position_embeddings=256,
        sequence_length=64, micro_batch_size=2, data_parallel_size=4,
        tensor_parallel_size=2, synthetic_data=True, max_grad_norm=1.0,
        grad_allreduce_dtype=dtype, learning_rate=1e-3,
    )
    kw.update(over)
    return ScaleTorchTPUArguments(**kw)


def _build_spmd(dtype, tx=None, dp=4, tp=2):
    import optax

    from scaletorch_tpu.models import llama
    from scaletorch_tpu.parallel.spmd import make_spmd_train_step, shard_params
    from scaletorch_tpu.trainer.trainer import build_model_config

    cfg = _tiny_cfg(dtype, data_parallel_size=dp, tensor_parallel_size=tp)
    model_cfg = build_model_config(cfg)
    mm = MeshManager(dp=dp, tp=tp)
    params = llama.init_params(jax.random.PRNGKey(0), model_cfg)
    tx = tx if tx is not None else optax.adamw(1e-3)
    step_fn, p_specs, o_specs = make_spmd_train_step(
        mm, llama.forward, model_cfg, tx, params, max_grad_norm=1.0,
        grad_allreduce_dtype=dtype, donate=False,
    )
    p = shard_params(mm, params, p_specs)
    o = shard_params(mm, tx.init(params), o_specs)
    return step_fn, p, o, params, tx


def _batch(seed=0, accum=1, rows=8, seq=64, vocab=512):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, vocab, size=(accum, rows, seq))
    return {
        "input_ids": jnp.asarray(ids, jnp.int32),
        "target_ids": jnp.asarray(np.roll(ids, -1, axis=-1), jnp.int32),
        "position_ids": jnp.broadcast_to(
            jnp.arange(seq, dtype=jnp.int32)[None], (accum, seq)
        ),
    }


@pytest.fixture(scope="module")
def sgd_step_pair():
    """fp32 + int8 SPMD steps with lr-1 SGD, so one step's param delta IS
    the (clipped) gradient — the grad cosine-similarity probe."""
    import optax

    pair = {}
    for dtype in ("fp32", "int8"):
        pair[dtype] = _build_spmd(dtype, tx=optax.sgd(1.0))
    return pair


class TestTrainStepParity:
    def test_grad_cosine_vs_fp32(self, devices8, sgd_step_pair):
        batch = _batch(7)
        deltas = {}
        for dtype, (step_fn, p, o, p_host, _) in sgd_step_pair.items():
            p2, _, m = step_fn(p, o, batch)
            delta = jax.tree.map(lambda a, b: np.asarray(a) - np.asarray(b),
                                 p2, p)
            deltas[dtype] = np.concatenate(
                [leaf.ravel() for leaf in jax.tree_util.tree_leaves(delta)]
            )
            assert float(m["update_skipped"]) == 0.0
        a, b = deltas["fp32"], deltas["int8"]
        cos = np.dot(a, b) / (np.linalg.norm(a) * np.linalg.norm(b))
        assert cos >= 0.999, cos

    def test_short_run_loss_parity_no_skips(self, devices8):
        batch = _batch(11)
        curves = {}
        for dtype in ("fp32", "int8"):
            step_fn, p, o, _, _ = _build_spmd(dtype)
            losses, skipped = [], 0.0
            for _ in range(5):
                p, o, m = step_fn(p, o, batch)
                losses.append(float(m["loss"]))
                skipped += float(m["update_skipped"])
            curves[dtype] = losses
            assert skipped == 0.0, dtype
        diff = np.abs(np.array(curves["fp32"]) - np.array(curves["int8"]))
        assert diff.max() < 5e-3, curves
        # and training actually progressed
        assert curves["int8"][-1] < curves["int8"][0]

    def test_bf16_mode_runs(self, devices8):
        step_fn, p, o, _, _ = _build_spmd("bf16")
        p, o, m = step_fn(p, o, _batch(13))
        assert np.isfinite(float(m["loss"]))
        assert float(m["update_skipped"]) == 0.0


class TestWireBytes:
    def test_int8_dp_wire_bytes_3x_lower(self, devices8):
        """Compiled-HLO attestation: on a pure-dp mesh every nontrivial
        gradient collective IS the dp all-reduce; int8 must move >= ~3x
        fewer wire bytes than fp32 (ISSUE 6 acceptance — measured ~4x
        minus the scale overhead and the shared scalar reductions)."""
        import optax

        from scaletorch_tpu.models import llama
        from scaletorch_tpu.parallel.spmd import make_spmd_train_step
        from scaletorch_tpu.trainer.trainer import build_model_config

        totals = {}
        for dtype in ("fp32", "int8"):
            cfg = _tiny_cfg(dtype, data_parallel_size=8,
                            tensor_parallel_size=1)
            model_cfg = build_model_config(cfg)
            mm = MeshManager(dp=8)
            params = llama.init_params(jax.random.PRNGKey(0), model_cfg)
            tx = optax.sgd(1.0)
            step_fn, _, _ = make_spmd_train_step(
                mm, llama.forward, model_cfg, tx, params, max_grad_norm=1.0,
                grad_allreduce_dtype=dtype, donate=False,
            )
            batch = {
                "input_ids": jax.ShapeDtypeStruct((1, 8, 64), jnp.int32),
                "target_ids": jax.ShapeDtypeStruct((1, 8, 64), jnp.int32),
                "position_ids": jax.ShapeDtypeStruct((1, 64), jnp.int32),
            }
            pshape = jax.eval_shape(lambda: params)
            oshape = jax.eval_shape(tx.init, params)
            hlo = step_fn.lower(pshape, oshape, batch).compile().as_text()
            totals[dtype] = collective_wire_bytes(hlo)
        ratio = totals["fp32"]["total"] / max(totals["int8"]["total"], 1.0)
        assert ratio >= 3.0, (ratio, totals)
        # and the int8 build really carries int8 payloads
        assert any(dt == "s8" for _, dt in totals["int8"]["by_op"])


class TestDeclarativeQuantizedStep:
    def test_dp_jit_path_parity(self, devices8):
        """make_train_step's bf16/int8 form (explicit shard_map reduction,
        replicated params) matches its own fp32 form."""
        import optax

        from scaletorch_tpu.models import llama
        from scaletorch_tpu.trainer.train_step import make_train_step
        from scaletorch_tpu.trainer.trainer import build_model_config

        cfg = _tiny_cfg("fp32", data_parallel_size=1, tensor_parallel_size=1)
        model_cfg = build_model_config(cfg)
        mm = MeshManager(dp=8)
        params = llama.init_params(jax.random.PRNGKey(1), model_cfg)
        # no position_ids: the declarative step's data_spec applies to
        # every batch leaf, so all leaves share the [accum, rows, seq] rank
        # (same contract as the fp32 mesh path).
        batch = {k: v for k, v in _batch(17, accum=2).items()
                 if k != "position_ids"}
        curves = {}
        for dtype in ("fp32", "int8"):
            tx = optax.adamw(1e-3)
            step = make_train_step(
                llama.forward, model_cfg, tx, attention_backend="sdpa",
                donate=False, mesh=mm.mesh, data_spec=P(None, "dp", None),
                grad_allreduce_dtype=dtype,
            )
            p, o = params, tx.init(params)
            losses = []
            for _ in range(3):
                p, o, m = step(p, o, batch)
                losses.append(float(m["loss"]))
                assert float(m["update_skipped"]) == 0.0
            curves[dtype] = losses
        diff = np.abs(np.array(curves["fp32"]) - np.array(curves["int8"]))
        assert diff.max() < 5e-3, curves

    def test_quantized_needs_mesh(self):
        import optax

        from scaletorch_tpu.models import llama
        from scaletorch_tpu.trainer.train_step import make_train_step
        from scaletorch_tpu.trainer.trainer import build_model_config

        cfg = _tiny_cfg("fp32")
        model_cfg = build_model_config(cfg)
        with pytest.raises(ValueError, match="mesh"):
            make_train_step(
                llama.forward, model_cfg, optax.sgd(1.0),
                grad_allreduce_dtype="int8",
            )
