"""Ulysses (all-to-all head-scatter) context parallelism.

A capability beyond the reference (SURVEY.md §5: the reference has "no
Ulysses"): two all-to-alls swap sequence sharding for head sharding and
each rank runs one full-sequence attention. Goldens against full SDPA on
the virtual 8-device mesh, forward and backward, plus the GQA-divisible
guard and an end-to-end Trainer run.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from scaletorch_tpu.models.layers import sdpa_attention
from scaletorch_tpu.ops.ulysses import ulysses_attention
from scaletorch_tpu.parallel.mesh import MeshManager

QKV = P(None, None, "cp", None)


def make_qkv(hq=4, hkv=2, s=32, d=16, b=2, seed=0):
    key = jax.random.PRNGKey(seed)
    q = jax.random.normal(key, (b, hq, s, d))
    k = jax.random.normal(jax.random.fold_in(key, 1), (b, hkv, s, d))
    v = jax.random.normal(jax.random.fold_in(key, 2), (b, hkv, s, d))
    return q, k, v


class TestUlysses:
    @pytest.mark.slow
    @pytest.mark.parametrize("cp,dp,hq,hkv", [(2, 4, 4, 2), (4, 2, 8, 4)])
    def test_forward_matches_sdpa(self, cp, dp, hq, hkv):
        q, k, v = make_qkv(hq=hq, hkv=hkv)
        ref = sdpa_attention(q, k, v, causal=True)
        mm = MeshManager(cp=cp, dp=dp)
        f = jax.shard_map(
            lambda q, k, v: ulysses_attention(q, k, v, impl="xla"),
            mesh=mm.mesh, in_specs=(QKV,) * 3, out_specs=QKV,
        )
        np.testing.assert_allclose(f(q, k, v), ref, atol=2e-5)

    @pytest.mark.slow
    def test_backward_matches_sdpa(self):
        q, k, v = make_qkv(hq=8, hkv=4)
        do = jax.random.normal(jax.random.PRNGKey(3), q.shape)
        mm = MeshManager(cp=4, dp=2)

        def ref_loss(q, k, v):
            return jnp.sum(sdpa_attention(q, k, v, causal=True) * do)

        def ul_loss(q, k, v, d):
            return jnp.sum(ulysses_attention(q, k, v, impl="xla") * d)

        g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        g = jax.shard_map(
            lambda q, k, v, d: jax.grad(ul_loss, argnums=(0, 1, 2))(q, k, v, d),
            mesh=mm.mesh, in_specs=(QKV,) * 4, out_specs=(QKV,) * 3,
        )(q, k, v, do)
        for a, b in zip(g_ref, g):
            np.testing.assert_allclose(a, b, atol=1e-5)

    @pytest.mark.slow
    def test_pallas_blocks_match(self):
        q, k, v = make_qkv(hq=4, hkv=2, s=64)
        ref = sdpa_attention(q, k, v, causal=True)
        mm = MeshManager(cp=2, dp=4)
        f = jax.shard_map(
            lambda q, k, v: ulysses_attention(q, k, v, impl="pallas",
                                              interpret=True),
            mesh=mm.mesh, in_specs=(QKV,) * 3, out_specs=QKV,
        )
        np.testing.assert_allclose(f(q, k, v), ref, atol=2e-5)

    def test_kv_head_divisibility_guard(self):
        q, k, v = make_qkv(hq=8, hkv=2)  # hkv 2 < cp 4
        mm = MeshManager(cp=4, dp=2)
        with pytest.raises(ValueError, match="ring"):
            jax.shard_map(
                lambda q, k, v: ulysses_attention(q, k, v, impl="xla"),
                mesh=mm.mesh, in_specs=(QKV,) * 3, out_specs=QKV,
            )(q, k, v)

    @pytest.mark.slow
    def test_trainer_ulysses_matches_dp_only_loss(self):
        """End-to-end: cp=2 Ulysses Trainer (contiguous layout, no host
        permutation) reproduces the dp-only loss."""
        from scaletorch_tpu.benchmark import make_bench_args
        from scaletorch_tpu.trainer.trainer import Trainer

        losses = {}
        for name, extra in {
            "dp8": dict(dp=8, micro_bs=1),
            "ulysses": dict(dp=4, cp=2, micro_bs=2,
                            extra={"attention_backend": "ulysses"}),
        }.items():
            t = Trainer(make_bench_args("dense-tiny", seq=64,
                                        dtype="float32", **extra))
            try:
                assert not t._zigzag_cp  # head ownership: no permutation
                it = iter(t.loader)
                for _ in range(2):
                    batch = t._device_batch(next(it))
                    t.params, t.opt_state, m = t.step_fn(
                        t.params, t.opt_state, batch)
                losses[name] = float(m["loss"])
            finally:
                t.close()
        assert losses["ulysses"] == pytest.approx(losses["dp8"], rel=2e-4)
