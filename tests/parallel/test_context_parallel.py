"""Ring attention correctness: golden numerics vs single-device SDPA.

The reference validates its blockwise fwd/bwd math single-process
(tests/parallel/test_context_parallel.py:72-106); here the real ring —
ppermute rotations, causal skip, LSE merge, dual-ring backward — runs on
the virtual 8-device mesh and is checked against full-sequence SDPA.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from scaletorch_tpu.models.layers import sdpa_attention
from scaletorch_tpu.models.llama import LlamaConfig, forward, init_params
from scaletorch_tpu.ops.ring_attention import ring_attention
from scaletorch_tpu.parallel.mesh import MeshManager

QKV_SPEC = P(None, None, "cp", None)


def make_qkv(hq=4, hkv=2, s=32, d=16, b=2, seed=0):
    key = jax.random.PRNGKey(seed)
    q = jax.random.normal(key, (b, hq, s, d))
    k = jax.random.normal(jax.random.fold_in(key, 1), (b, hkv, s, d))
    v = jax.random.normal(jax.random.fold_in(key, 2), (b, hkv, s, d))
    return q, k, v


class TestRingAttention:
    @pytest.mark.slow
    @pytest.mark.parametrize("cp,dp", [(2, 4), (4, 2), (8, 1)])
    def test_forward_matches_sdpa(self, cp, dp):
        q, k, v = make_qkv()
        ref = sdpa_attention(q, k, v, causal=True)
        mm = MeshManager(cp=cp, dp=dp)
        f = jax.shard_map(
            lambda q, k, v: ring_attention(q, k, v, "cp"),
            mesh=mm.mesh, in_specs=(QKV_SPEC,) * 3, out_specs=QKV_SPEC,
        )
        np.testing.assert_allclose(f(q, k, v), ref, atol=2e-5)

    @pytest.mark.slow
    def test_backward_matches_sdpa(self):
        q, k, v = make_qkv()
        do = jax.random.normal(jax.random.PRNGKey(3), q.shape)
        mm = MeshManager(cp=4, dp=2)

        def ref_loss(q, k, v):
            return jnp.sum(sdpa_attention(q, k, v, causal=True) * do)

        def ring_loss(q, k, v, do_l):
            return jnp.sum(ring_attention(q, k, v, "cp") * do_l)

        g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        g = jax.shard_map(
            lambda q, k, v, d: jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v, d),
            mesh=mm.mesh, in_specs=(QKV_SPEC,) * 4, out_specs=(QKV_SPEC,) * 3,
        )(q, k, v, do)
        for a, b in zip(g_ref, g):
            np.testing.assert_allclose(a, b, atol=5e-6)

    @pytest.mark.slow
    def test_mha_no_gqa(self):
        q, k, v = make_qkv(hq=4, hkv=4)
        ref = sdpa_attention(q, k, v, causal=True)
        mm = MeshManager(cp=4, dp=2)
        f = jax.shard_map(
            lambda q, k, v: ring_attention(q, k, v, "cp"),
            mesh=mm.mesh, in_specs=(QKV_SPEC,) * 3, out_specs=QKV_SPEC,
        )
        np.testing.assert_allclose(f(q, k, v), ref, atol=2e-5)

    @pytest.mark.slow
    @pytest.mark.parametrize("cp,dp", [(2, 4), (4, 2)])
    def test_pallas_forward_matches_sdpa(self, cp, dp):
        """Flash-kernel blocks inside the ring (interpret mode on CPU)."""
        q, k, v = make_qkv()
        ref = sdpa_attention(q, k, v, causal=True)
        mm = MeshManager(cp=cp, dp=dp)
        f = jax.shard_map(
            lambda q, k, v: ring_attention(q, k, v, "cp", True, None,
                                           "pallas", True),
            mesh=mm.mesh, in_specs=(QKV_SPEC,) * 3, out_specs=QKV_SPEC,
        )
        np.testing.assert_allclose(f(q, k, v), ref, atol=2e-5)

    @pytest.mark.slow
    def test_pallas_backward_matches_sdpa(self):
        q, k, v = make_qkv()
        do = jax.random.normal(jax.random.PRNGKey(3), q.shape)
        mm = MeshManager(cp=4, dp=2)

        def ref_loss(q, k, v):
            return jnp.sum(sdpa_attention(q, k, v, causal=True) * do)

        def ring_loss(q, k, v, do_l):
            return jnp.sum(
                ring_attention(q, k, v, "cp", True, None, "pallas", True)
                * do_l
            )

        g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        g = jax.shard_map(
            lambda q, k, v, d: jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v, d),
            mesh=mm.mesh, in_specs=(QKV_SPEC,) * 4, out_specs=(QKV_SPEC,) * 3,
        )(q, k, v, do)
        for a, b in zip(g_ref, g):
            np.testing.assert_allclose(a, b, atol=1e-5)

    def test_non_causal_rejected(self):
        q, k, v = make_qkv()
        mm = MeshManager(cp=2, dp=4)
        with pytest.raises(NotImplementedError, match="causal-only"):
            jax.shard_map(
                lambda q, k, v: ring_attention(q, k, v, "cp", False),
                mesh=mm.mesh, in_specs=(QKV_SPEC,) * 3, out_specs=QKV_SPEC,
            )(q, k, v)


class TestZigzagRingAttention:
    """Load-balanced stripe layout: goldens run the zigzag schedule on
    host-permuted inputs and un-permute before comparing to full SDPA."""

    @staticmethod
    def _permuted(arrs, s, cp):
        from scaletorch_tpu.parallel.zigzag import zigzag_order

        order = zigzag_order(s, cp)
        return [np.asarray(a)[:, :, order] for a in arrs]

    @pytest.mark.slow
    @pytest.mark.parametrize("cp,dp,impl,interp", [
        (2, 4, "xla", False), (4, 2, "xla", False),
        (2, 4, "pallas", True), (4, 2, "pallas", True),
    ])
    def test_forward_matches_sdpa(self, cp, dp, impl, interp):
        from scaletorch_tpu.parallel.zigzag import zigzag_restore

        q, k, v = make_qkv()
        s = q.shape[2]
        ref = sdpa_attention(q, k, v, causal=True)
        qz, kz, vz = self._permuted((q, k, v), s, cp)
        mm = MeshManager(cp=cp, dp=dp)
        f = jax.shard_map(
            lambda q, k, v: ring_attention(q, k, v, "cp", True, None,
                                           impl, interp, "zigzag"),
            mesh=mm.mesh, in_specs=(QKV_SPEC,) * 3, out_specs=QKV_SPEC,
        )
        out = np.asarray(f(qz, kz, vz))[:, :, zigzag_restore(s, cp)]
        np.testing.assert_allclose(out, ref, atol=2e-5)

    @pytest.mark.slow
    @pytest.mark.parametrize("cp,dp,impl,interp", [
        (4, 2, "xla", False), (4, 2, "pallas", True),
    ])
    def test_backward_matches_sdpa(self, cp, dp, impl, interp):
        from scaletorch_tpu.parallel.zigzag import zigzag_restore

        q, k, v = make_qkv()
        s = q.shape[2]
        do = jax.random.normal(jax.random.PRNGKey(3), q.shape)
        qz, kz, vz, doz = self._permuted((q, k, v, do), s, cp)
        mm = MeshManager(cp=cp, dp=dp)

        def ref_loss(q, k, v):
            return jnp.sum(sdpa_attention(q, k, v, causal=True) * do)

        def ring_loss(q, k, v, do_l):
            return jnp.sum(
                ring_attention(q, k, v, "cp", True, None, impl, interp,
                               "zigzag") * do_l)

        g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        g = jax.shard_map(
            lambda q, k, v, d: jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v, d),
            mesh=mm.mesh, in_specs=(QKV_SPEC,) * 4, out_specs=(QKV_SPEC,) * 3,
        )(qz, kz, vz, doz)
        inv = zigzag_restore(s, cp)
        for a, b in zip(g_ref, g):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b)[:, :, inv],
                                       atol=1e-5)

    def test_order_restore_roundtrip(self):
        from scaletorch_tpu.parallel.zigzag import (
            zigzag_batch, zigzag_order, zigzag_restore,
        )

        order = zigzag_order(32, 4)
        assert sorted(order.tolist()) == list(range(32))
        # rank 0's slice is stripes 0 and 7
        assert order[:8].tolist() == [0, 1, 2, 3, 28, 29, 30, 31]
        x = np.arange(32)
        assert (x[order][zigzag_restore(32, 4)] == x).all()
        batch = {"input_ids": np.arange(64).reshape(2, 32),
                 "position_ids": np.arange(32)[None, :]}
        z = zigzag_batch(batch, 4)
        assert (z["input_ids"][:, zigzag_restore(32, 4)]
                == batch["input_ids"]).all()
        # cp=1 is the identity (and no copy semantics surprises)
        assert zigzag_batch(batch, 1) is batch
        # a non-per-token field must raise loudly, even when its last axis
        # happens to divide 2*cp (ADVICE r3: silent wrong permutation)
        bad = dict(batch, routing_bias=np.zeros((2, 16)))
        with pytest.raises(ValueError, match="routing_bias"):
            zigzag_batch(bad, 4)

    def test_odd_local_sequence_rejected(self):
        q, k, v = make_qkv(s=4)  # local seq 1 at cp=4
        mm = MeshManager(cp=4, dp=2)
        with pytest.raises(ValueError, match="even local sequence"):
            jax.shard_map(
                lambda q, k, v: ring_attention(q, k, v, "cp", True, None,
                                               "xla", False, "zigzag"),
                mesh=mm.mesh, in_specs=(QKV_SPEC,) * 3, out_specs=QKV_SPEC,
            )(q, k, v)

    @pytest.mark.slow
    def test_contiguous_trainer_unaffected_by_zigzag_env(self, monkeypatch):
        """The layout must be pinned into each step from ITS config at
        build time: a contiguous Trainer constructed before a zigzag one
        (whose __init__ flips the process-global env var) but traced
        after it must still run the contiguous schedule."""
        import os

        from scaletorch_tpu.benchmark import make_bench_args
        from scaletorch_tpu.trainer.trainer import Trainer

        monkeypatch.setenv("SCALETORCH_TPU_CP_LAYOUT", "contiguous")
        contig = Trainer(make_bench_args(
            "dense-tiny", seq=64, dtype="float32", dp=4, cp=2, micro_bs=2,
            extra={"cp_layout": "contiguous"}))
        zz = Trainer(make_bench_args(
            "dense-tiny", seq=64, dtype="float32", dp=4, cp=2, micro_bs=2))
        zz.close()
        # ADVICE r3: the Trainer must NOT mutate the process-global layout
        # env — the step pins its layout via the ring_zigzag/ring_contiguous
        # registry aliases instead
        assert os.environ["SCALETORCH_TPU_CP_LAYOUT"] == "contiguous"
        ref = Trainer(make_bench_args(
            "dense-tiny", seq=64, dtype="float32", dp=8, micro_bs=1))
        try:
            losses = {}
            for name, t in {"dp8": ref, "contig": contig}.items():
                it = iter(t.loader)
                for _ in range(2):
                    batch = t._device_batch(next(it))
                    t.params, t.opt_state, m = t.step_fn(
                        t.params, t.opt_state, batch)
                losses[name] = float(m["loss"])
        finally:
            contig.close()
            ref.close()
        # contig's step first traced AFTER the env flipped to zigzag; a
        # trace-time env read would run the zigzag schedule on contiguous
        # shards and corrupt the loss
        assert losses["contig"] == pytest.approx(losses["dp8"], rel=2e-4)

    @pytest.mark.slow
    def test_trainer_zigzag_matches_dp_only_loss(self, monkeypatch):
        """End-to-end: a cp=2 zigzag Trainer (pinned backend alias + host
        batch permutation + ring schedule) reproduces the dp-only loss —
        the per-token losses are a permutation, so the mean is identical."""
        from scaletorch_tpu.benchmark import make_bench_args
        from scaletorch_tpu.trainer.trainer import Trainer

        # prove the pinned alias wins even against a contrary env default
        monkeypatch.setenv("SCALETORCH_TPU_CP_LAYOUT", "contiguous")

        losses = {}
        for name, shape in {
            "dp8": dict(dp=8, micro_bs=1),
            "zz": dict(dp=4, cp=2, micro_bs=2),
        }.items():
            cfg = make_bench_args("dense-tiny", seq=64, dtype="float32",
                                  **shape)
            assert cfg.cp_layout == "zigzag"  # the default
            t = Trainer(cfg)
            try:
                it = iter(t.loader)
                for _ in range(2):
                    batch = t._device_batch(next(it))
                    t.params, t.opt_state, m = t.step_fn(
                        t.params, t.opt_state, batch)
                losses[name] = float(m["loss"])
            finally:
                t.close()
        assert losses["zz"] == pytest.approx(losses["dp8"], rel=2e-4)


@pytest.mark.slow
class TestCpModelParity:
    def test_cp_forward_matches_dense(self):
        """Full decoder under cp=2 x tp=2 (+SP) vs single-device: the model
        consumes seq-sharded inputs + positions and ring attention."""
        cfg = LlamaConfig(
            vocab_size=128, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            dtype=jnp.float32,
        )
        params = init_params(jax.random.PRNGKey(0), cfg)
        ids = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 128)
        positions = jnp.arange(32, dtype=jnp.int32)
        ref = forward(params, ids, cfg)

        from scaletorch_tpu.parallel.tensor_parallel import llama_param_specs

        mm = MeshManager(cp=2, tp=2, dp=2)
        specs = llama_param_specs(cfg)

        def cp_fwd(p, i, pos):
            return forward(
                p, i, cfg, positions=pos, attention_backend="ring",
                tp_axis="tp", sequence_parallel=True,
            )

        f = jax.shard_map(
            cp_fwd, mesh=mm.mesh,
            in_specs=(specs, P(None, "cp"), P("cp")),
            out_specs=P(None, "cp", "tp"),
        )
        out = f(params, ids, positions)
        np.testing.assert_allclose(out, ref, atol=3e-5)

    def test_cp_train_step_matches_single_device(self):
        from scaletorch_tpu.config import ScaleTorchTPUArguments
        from scaletorch_tpu.parallel.spmd import make_spmd_train_step, shard_params
        from scaletorch_tpu.trainer.optimizer import create_optimizer
        from scaletorch_tpu.trainer.train_step import make_train_step

        cfg = LlamaConfig(
            vocab_size=128, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            dtype=jnp.float32,
        )
        params = init_params(jax.random.PRNGKey(0), cfg)
        args = ScaleTorchTPUArguments(
            total_train_steps=10, learning_rate=1e-3, max_grad_norm=1.0
        )
        tx_ref, _ = create_optimizer(args)
        ref_step = make_train_step(forward, cfg, tx_ref, donate=False)

        mm = MeshManager(dp=2, cp=2, tp=2)
        tx, _ = create_optimizer(args, include_clip=False)
        step, p_specs, o_specs = make_spmd_train_step(
            mm, forward, cfg, tx, params,
            attention_backend="ring", sequence_parallel=True,
            max_grad_norm=1.0, donate=False,
        )
        rng = np.random.default_rng(0)
        toks = rng.integers(0, 128, size=(2, 2, 33), dtype=np.int32)
        batch = {
            "input_ids": jnp.asarray(toks[:, :, :-1]),
            "target_ids": jnp.asarray(toks[:, :, 1:]),
            "position_ids": jnp.broadcast_to(
                jnp.arange(32, dtype=jnp.int32), (2, 32)
            ),
        }
        p1, _, m1 = ref_step(params, tx_ref.init(params), batch)
        p2, _, m2 = step(
            shard_params(mm, params, p_specs),
            shard_params(mm, tx.init(params), o_specs),
            batch,
        )
        assert float(m1["loss"]) == pytest.approx(float(m2["loss"]), rel=1e-5)
        for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(jax.device_get(p2))):
            np.testing.assert_allclose(a, b, atol=5e-5)
