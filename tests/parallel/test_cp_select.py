"""resolve_cp_backend: the hand-tuned docs table, computed and attested.

The resolver must reproduce every row of the old docs/long_context.md §4
table on the topologies it covered (ISSUE 6 acceptance), read DCN hops
off a real mesh, and never override an explicit operator choice.
"""

import json
import os

import numpy as np
import pytest

from scaletorch_tpu.parallel.cp_select import (
    CPChoice,
    EXTREME_SEQ_THRESHOLD,
    cp_cross_host_hops,
    resolve_cp_backend,
    ring_wire_bytes,
    ulysses_wire_bytes,
)
from scaletorch_tpu.parallel.mesh import MeshManager

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _auto(**kw):
    kw.setdefault("cross_host_hops", 0)
    return resolve_cp_backend("auto", None, **kw)


class TestDocsTable:
    """One test per row of the hand-tuned table."""

    def test_default_long_context_is_ring_zigzag(self):
        c = _auto(cp=4, num_q_heads=16, num_kv_heads=8, seq_len=8192)
        assert (c.backend, c.layout) == ("ring", "zigzag")

    def test_many_kv_heads_is_ulysses(self):
        c = _auto(cp=4, num_q_heads=16, num_kv_heads=16, seq_len=8192)
        assert c.backend == "ulysses"
        assert c.layout == "contiguous"

    def test_cross_host_dcn_is_ulysses(self):
        c = _auto(cp=4, num_q_heads=16, num_kv_heads=8, seq_len=8192,
                  cross_host_hops=2)
        assert c.backend == "ulysses"
        assert "DCN" in c.reason

    def test_extreme_seq_is_ring(self):
        c = _auto(cp=4, num_q_heads=16, num_kv_heads=16,
                  seq_len=4 * EXTREME_SEQ_THRESHOLD)
        assert c.backend == "ring"


class TestConstraints:
    def test_explicit_request_always_honored(self):
        for backend in ("ring", "ulysses"):
            c = resolve_cp_backend(backend, None, cp=4, num_q_heads=16,
                                   num_kv_heads=8, seq_len=1 << 20)
            assert c.backend == backend

    def test_indivisible_heads_forces_ring(self):
        # even across DCN: ulysses cannot shard 8 kv heads over cp=3
        c = _auto(cp=3, num_q_heads=15, num_kv_heads=8, seq_len=8192,
                  cross_host_hops=2)
        assert c.backend == "ring"
        assert "divide" in c.reason

    def test_cp1_degenerate(self):
        assert _auto(cp=1, num_q_heads=16, num_kv_heads=8,
                     seq_len=8192).backend == "ring"

    def test_none_kv_heads_means_mha(self):
        # MHA at cp=4: ring moves cp*H/(2H) = 2x the bytes -> ulysses
        c = _auto(cp=4, num_q_heads=16, num_kv_heads=None, seq_len=8192)
        assert c.backend == "ulysses"

    def test_byte_model_gqa_ratio(self):
        # analytic sanity: ring/ulysses = cp*Hkv/(Hq+Hkv)
        r = ring_wire_bytes(4, 8192, 8, 64)
        u = ulysses_wire_bytes(4, 8192, 16, 8, 64)
        assert r / u == pytest.approx(4 * 8 / (16 + 8))


class TestTopologyProbe:
    def test_single_process_mesh_has_no_dcn_hops(self, devices8):
        mm = MeshManager(cp=4, dp=2, devices=devices8)
        assert cp_cross_host_hops(mm.mesh) == 0

    def test_mesh_resolution_end_to_end(self, devices8):
        mm = MeshManager(cp=4, dp=2, devices=devices8)
        c = resolve_cp_backend("auto", mm.mesh, cp=4, num_q_heads=16,
                               num_kv_heads=8, seq_len=8192)
        assert isinstance(c, CPChoice)
        assert c.backend == "ring"  # ICI, GQA, moderate seq

    def test_cp_axis_absent_means_zero_hops(self, devices8):
        from jax.sharding import Mesh

        mesh = Mesh(np.asarray(devices8), ("x",))
        assert cp_cross_host_hops(mesh) == 0


class TestCrossoverJSON:
    """The checked-in attestation must agree with the live resolver —
    the same contract tools/aot_cp_crossover.py --check enforces in CI."""

    @pytest.fixture()
    def data(self):
        path = os.path.join(REPO, "AOT_CP_CROSSOVER.json")
        if not os.path.exists(path):
            pytest.skip("AOT_CP_CROSSOVER.json not generated")
        with open(path) as f:
            return json.load(f)

    def test_rows_reproduce(self, data):
        for row in data["rows"]:
            c = _auto(cp=row["cp"], num_q_heads=row["hq"],
                      num_kv_heads=row["hkv"], seq_len=row["seq"])
            assert c.backend == row["resolved"], row["label"]

    def test_check_mode_passes(self):
        import subprocess
        import sys

        if not os.path.exists(os.path.join(REPO, "AOT_CP_CROSSOVER.json")):
            pytest.skip("AOT_CP_CROSSOVER.json not generated")
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "aot_cp_crossover.py"), "--check"],
            capture_output=True, text=True, timeout=300, cwd=REPO,
        )
        assert proc.returncode == 0, proc.stderr[-500:]
