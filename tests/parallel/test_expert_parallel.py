"""EP/MoE: routing invariants + dispatch round-trip + sharded goldens.

Mirrors reference tests/parallel/test_ep_comms.py invariants (split sums,
permutation property, local id ranges, :69-96) adapted to capacity-based
dispatch, and adds what the reference cannot test single-process: the
real all_to_all over an 8-virtual-device ep axis checked against the
single-device MoE forward.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from scaletorch_tpu.models.qwen3_moe import (
    Qwen3MoEConfig,
    forward,
    init_params,
    qwen3_moe_param_specs,
)
from scaletorch_tpu.parallel.expert_parallel import (
    dispatch_tokens,
    expert_capacity,
    gather_tokens,
    moe_mlp,
    sorted_dispatch_reference,
    top_k_routing,
    validate_ep_divisibility,
)
from scaletorch_tpu.parallel.mesh import MeshManager

CFG = Qwen3MoEConfig(
    vocab_size=128, hidden_size=32, intermediate_size=64,
    moe_intermediate_size=48, num_hidden_layers=2, num_attention_heads=4,
    num_key_value_heads=4, head_dim=8, num_experts=8, num_experts_per_tok=2,
    capacity_factor=8.0,  # large capacity -> no drops -> exact goldens
    dtype=jnp.float32, qk_norm=True, tie_word_embeddings=False,
)


class TestCapacity:
    def test_expert_capacity(self):
        assert expert_capacity(64, 8, 2, 1.0) == 16
        assert expert_capacity(64, 8, 2, 1.25) == 20
        assert expert_capacity(4, 64, 1, 1.0) == 1  # at least 1
        assert expert_capacity(8, 2, 1, 100.0) == 8  # at most N

    def test_validate_ep(self):
        validate_ep_divisibility(CFG, 4)
        with pytest.raises(ValueError, match="not divisible"):
            validate_ep_divisibility(CFG, 3)


class TestRouting:
    def setup_method(self):
        self.n, self.e, self.k = 32, 8, 2
        self.logits = jax.random.normal(jax.random.PRNGKey(0), (self.n, self.e))

    def test_dispatch_is_permutation_like(self):
        """Every kept (token, choice) occupies exactly one (expert, slot);
        no slot is double-booked (reference permutation invariant,
        test_ep_comms.py:69-96)."""
        cap = expert_capacity(self.n, self.e, self.k, 8.0)
        dispatch, combine, aux = top_k_routing(self.logits, self.k, cap)
        # no slot double-booked
        per_slot = jnp.sum(dispatch, axis=0)  # [E, C]
        assert float(jnp.max(per_slot)) <= 1.0
        # with huge capacity nothing is dropped: every token sends k copies
        per_token = jnp.sum(dispatch, axis=(1, 2))  # [N]
        np.testing.assert_allclose(per_token, self.k)
        assert float(aux["dropped_fraction"]) == 0.0

    def test_combine_weights_sum_to_one(self):
        cap = expert_capacity(self.n, self.e, self.k, 8.0)
        _, combine, _ = top_k_routing(self.logits, self.k, cap)
        np.testing.assert_allclose(
            jnp.sum(combine, axis=(1, 2)), 1.0, rtol=1e-6
        )

    def test_capacity_drops(self):
        """With capacity 1, at most E tokens survive (reference capacity
        semantics, moe.py:510-600)."""
        dispatch, _, aux = top_k_routing(self.logits, self.k, 1)
        assert float(jnp.sum(dispatch)) <= self.e
        assert float(aux["dropped_fraction"]) > 0.0
        per_slot = jnp.sum(dispatch, axis=0)
        assert float(jnp.max(per_slot)) <= 1.0

    def test_aux_loss_balanced_is_one(self):
        """Uniform router -> Switch aux loss == 1 (its minimum)."""
        logits = jnp.zeros((64, self.e))
        _, _, aux = top_k_routing(logits, 1, 64)
        np.testing.assert_allclose(float(aux["aux_loss"]), 1.0, rtol=1e-5)

    def test_sorted_dispatch_reference_invariants(self):
        """Sort-based path: grouped by expert, stable, counts sum to N
        (reference test_ep_comms.py invariants)."""
        ids = jax.random.randint(jax.random.PRNGKey(1), (self.n,), 0, self.e)
        x = jax.random.normal(jax.random.PRNGKey(2), (self.n, 4))
        sorted_x, sort_idx, counts = sorted_dispatch_reference(x, ids, self.e)
        assert int(jnp.sum(counts)) == self.n
        sorted_ids = ids[sort_idx]
        assert bool(jnp.all(jnp.diff(sorted_ids) >= 0))
        # permutation property: unsort restores
        restored = jnp.zeros_like(sorted_x).at[sort_idx].set(sorted_x)
        np.testing.assert_allclose(restored, x)


class TestDispatchRoundTrip:
    def test_local_round_trip_identity(self):
        """dispatch -> gather with identity experts == combine-weighted
        passthrough (= x when weights sum to 1 and nothing dropped)."""
        n, e, k, h = 16, 4, 2, 8
        logits = jax.random.normal(jax.random.PRNGKey(3), (n, e))
        x = jax.random.normal(jax.random.PRNGKey(4), (n, h))
        cap = expert_capacity(n, e, k, 8.0)
        dispatch, combine, _ = top_k_routing(logits, k, cap)
        slots = dispatch_tokens(x, dispatch)
        y = gather_tokens(slots, combine)
        np.testing.assert_allclose(y, x, rtol=1e-5)

    @pytest.mark.slow
    def test_ep_round_trip_matches_local(self):
        """The all_to_all dispatch over ep=4 must agree with the local
        (axis=None) path given identical routing."""
        n, e, k, h = 16, 8, 2, 8
        logits = jax.random.normal(jax.random.PRNGKey(5), (n, e))
        x = jax.random.normal(jax.random.PRNGKey(6), (n, h))
        cap = expert_capacity(n, e, k, 8.0)
        dispatch, combine, _ = top_k_routing(logits, k, cap)
        wkey = jax.random.PRNGKey(7)
        gate = jax.random.normal(wkey, (e, h, 6))
        up = jax.random.normal(jax.random.fold_in(wkey, 1), (e, h, 6))
        down = jax.random.normal(jax.random.fold_in(wkey, 2), (e, 6, h))

        ref = gather_tokens(moe_mlp(dispatch_tokens(x, dispatch), gate, up, down),
                            combine)

        mm = MeshManager(ep=4, dp=2)

        def body(x, d, c, g, u, dn):
            from scaletorch_tpu.parallel.tensor_parallel import pvary_missing

            # pre-vary over the data axes, as the SPMD step does for the
            # real training path (parallel/spmd.py)
            x, d, c, g, u, dn = (
                pvary_missing(t, ("dp", "ep")) for t in (x, d, c, g, u, dn)
            )
            slots = dispatch_tokens(x, d, axis="ep")
            out = moe_mlp(slots, g, u, dn)
            y = gather_tokens(out, c, axis="ep")
            # tokens were replicated over ep, so every rank holds the full
            # result; pmean collapses the (identical) copies
            return jax.lax.pmean(y, ("dp", "ep"))

        f = jax.shard_map(
            body, mesh=mm.mesh,
            in_specs=(P(), P(), P(), P("ep"), P("ep"), P("ep")),
            out_specs=P(),
        )
        np.testing.assert_allclose(
            f(x, dispatch, combine, gate, up, down), ref, rtol=1e-4, atol=1e-5
        )


@pytest.fixture(scope="module")
def moe_setup():
    params = init_params(jax.random.PRNGKey(0), CFG)
    ids = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, CFG.vocab_size)
    hidden, aux = forward(params, ids, CFG, return_hidden=True)
    logits = forward(params, ids, CFG)
    return params, ids, hidden, aux, logits


class TestQwen3MoEModel:
    def test_forward_shapes(self, moe_setup):
        params, ids, hidden, aux, logits = moe_setup
        assert hidden.shape == (4, 32, CFG.hidden_size)
        assert logits.shape == (4, 32, CFG.vocab_size)
        assert np.isfinite(float(aux))
        assert np.all(np.isfinite(np.asarray(logits)))

    def test_param_counts(self, moe_setup):
        params, *_ = moe_setup
        actual = sum(x.size for x in jax.tree.leaves(params))
        assert actual == CFG.num_params()
        assert CFG.num_active_params() < CFG.num_params()

    @pytest.mark.parametrize("tp", [1, 2])
    def test_ep_sharded_matches_single_device(self, moe_setup, tp):
        from scaletorch_tpu.parallel.tensor_parallel import pvary_missing

        params, ids, hidden_ref, aux_ref, _ = moe_setup
        mm = MeshManager(ep=2, tp=tp, dp=8 // (2 * tp))
        tp_axis = "tp" if tp > 1 else None
        specs = qwen3_moe_param_specs(CFG, tp_axis=tp_axis, ep_axis="ep")
        axes = ("dp", "ep") + (("tp",) if tp > 1 else ())

        def body(p, i):
            # pre-vary over data axes (the SPMD step's contract)
            p = jax.tree.map(lambda x: pvary_missing(x, axes), p)
            i = pvary_missing(i, axes)
            h, aux = forward(p, i, CFG, tp_axis=tp_axis, ep_axis="ep",
                             return_hidden=True)
            # tokens replicated over ep in this test -> identical copies
            return (jax.lax.pmean(h, axes[1:]),
                    jax.lax.pmean(pvary_missing(aux, axes), axes))

        f = jax.jit(jax.shard_map(
            body, mesh=mm.mesh,
            in_specs=(specs, P("dp", None)),
            out_specs=(P("dp", None, None), P()),
        ))
        h, aux = f(params, ids)
        np.testing.assert_allclose(h, hidden_ref, rtol=2e-4, atol=2e-5)
        # fp32 accumulation-order noise can flip a marginal top-k choice,
        # discretely shifting the load-balance term — aux only matches
        # loosely; the tight hidden-state match above is the correctness
        # guarantee.
        np.testing.assert_allclose(float(aux), float(aux_ref), rtol=0.15)


@pytest.mark.slow
class TestMoETrainStep:
    def test_ep_gradients_match_single_device(self):
        """ADVICE r1: golden for the ep-sharded gradient scaling in the
        SPMD step (pmean over data axes + /ep for expert leaves,
        spmd.py:311-318) — one SGD update under ep=2 must equal the
        single-device update on identical data (mirrors the PP gradient
        goldens)."""
        import optax

        from scaletorch_tpu.config import ScaleTorchTPUArguments
        from scaletorch_tpu.models.qwen3_moe import lm_head_weight
        from scaletorch_tpu.parallel.spmd import make_spmd_train_step, shard_params
        from scaletorch_tpu.parallel.tensor_parallel import (
            fused_vocab_parallel_cross_entropy,
        )
        from scaletorch_tpu.trainer.optimizer import create_optimizer

        params = init_params(jax.random.PRNGKey(0), CFG)
        tcfg = ScaleTorchTPUArguments(
            learning_rate=1e-2, total_train_steps=10, warmup_steps=0,
            optimizer_name="sgd",
        )
        rng = np.random.default_rng(0)
        rows, seq = 8, 16  # rows = dp * ep
        toks = rng.integers(0, CFG.vocab_size, (1, rows, seq + 1))
        batch = {
            "input_ids": toks[:, :, :-1].astype(np.int32),
            "target_ids": toks[:, :, 1:].astype(np.int32),
            "position_ids": np.broadcast_to(
                np.arange(seq, dtype=np.int32), (1, seq)
            ).copy(),
        }
        pos = jnp.arange(seq, dtype=jnp.int32)

        # single-device reference with the SPMD step's exact loss form
        def ref_loss(p):
            hidden, aux = forward(
                p, jnp.asarray(batch["input_ids"][0]), CFG,
                positions=pos, return_hidden=True,
            )
            head = lm_head_weight(p, CFG, None)
            ce = fused_vocab_parallel_cross_entropy(
                hidden, head, jnp.asarray(batch["target_ids"][0]), axis=None
            )
            return ce + aux

        tx, _ = create_optimizer(tcfg, include_clip=False)
        grads_ref = jax.grad(ref_loss)(params)
        updates, _ = tx.update(grads_ref, tx.init(params), params)
        p_ref = optax.apply_updates(params, updates)

        mm = MeshManager(ep=2, dp=4)
        specs = qwen3_moe_param_specs(CFG, tp_axis="tp", ep_axis="ep")
        step_fn, p_specs, o_specs = make_spmd_train_step(
            mm, forward, CFG, tx, params,
            donate=False, param_specs=specs,
            model_kwargs={"ep_axis": "ep"},
        )
        p2, _, metrics = step_fn(
            shard_params(mm, params, p_specs),
            shard_params(mm, tx.init(params), o_specs),
            batch,
        )
        assert float(metrics["loss"]) == pytest.approx(
            float(ref_loss(params)), rel=1e-5
        )
        for a, b in zip(jax.tree.leaves(p_ref), jax.tree.leaves(jax.device_get(p2))):
            np.testing.assert_allclose(a, b, atol=2e-5)

    def test_spmd_step_with_ep(self):
        from scaletorch_tpu.config import ScaleTorchTPUArguments
        from scaletorch_tpu.parallel.spmd import make_spmd_train_step, shard_params
        from scaletorch_tpu.trainer.optimizer import create_optimizer

        mm = MeshManager(ep=2, tp=2, dp=2)
        params = init_params(jax.random.PRNGKey(0), CFG)
        tcfg = ScaleTorchTPUArguments(
            learning_rate=1e-3, total_train_steps=10, warmup_steps=0
        )
        tx, _ = create_optimizer(tcfg, include_clip=False)
        specs = qwen3_moe_param_specs(CFG, tp_axis="tp", ep_axis="ep")
        step_fn, p_specs, o_specs = make_spmd_train_step(
            mm, forward, CFG, tx, params,
            max_grad_norm=1.0, donate=False,
            param_specs=specs,
            model_kwargs={"ep_axis": "ep", "return_moe_stats": True},
        )
        params_s = shard_params(mm, params, p_specs)
        opt_state = shard_params(mm, tx.init(params), o_specs)

        rng = np.random.default_rng(0)
        accum, rows, seq = 2, 4, 16  # rows = dp*ep
        ids = rng.integers(0, CFG.vocab_size, (accum, rows, seq + 1))
        batch = {
            "input_ids": ids[:, :, :-1].astype(np.int32),
            "target_ids": ids[:, :, 1:].astype(np.int32),
            "position_ids": np.broadcast_to(
                np.arange(seq, dtype=np.int32), (accum, seq)
            ).copy(),
        }
        p2, o2, metrics = step_fn(params_s, opt_state, batch)
        assert np.isfinite(float(metrics["loss"]))
        assert np.isfinite(float(metrics["grad_norm"]))
        # routing health surfaces in the step metrics (VERDICT r1 weak #5)
        assert 0.0 <= float(metrics["moe_dropped_fraction"]) <= 1.0
        assert float(metrics["moe_load_cv"]) >= 0.0
        delta = jax.tree.map(
            lambda a, b: float(jnp.max(jnp.abs(jnp.asarray(a) - b))), p2, params
        )
        assert max(jax.tree.leaves(delta)) > 0


class TestIndexedDispatch:
    """Index-based (scatter/gather) dispatch vs the one-hot einsums: the
    two forms must make IDENTICAL routing decisions, drops, and outputs —
    the index form just avoids the O(N·E·C·H) one-hot work that dominates
    at large expert counts (Qwen3-30B-A3B: ~4.5x the expert FLOPs)."""

    def _problem(self, n=48, e=8, k=2, h=16, seed=0):
        key = jax.random.PRNGKey(seed)
        logits = jax.random.normal(key, (n, e))
        x = jax.random.normal(jax.random.fold_in(key, 1), (n, h))
        return logits, x

    @pytest.mark.parametrize("cf", [8.0, 0.5])  # no-drop AND forced drops
    def test_single_rank_matches_onehot(self, cf):
        from scaletorch_tpu.parallel.expert_parallel import (
            dispatch_tokens_indexed,
            gather_tokens_indexed,
            top_k_routing_indexed,
        )

        logits, x = self._problem()
        n, e, k = logits.shape[0], logits.shape[1], 2
        cap = expert_capacity(n, e, k, cf)
        dispatch, combine, aux_ref = top_k_routing(logits, k, cap)
        routing, aux = top_k_routing_indexed(logits, k, cap)
        for key in aux_ref:
            np.testing.assert_allclose(aux[key], aux_ref[key], rtol=1e-6)

        slots_ref = dispatch_tokens(x, dispatch)
        slots = dispatch_tokens_indexed(
            x, routing, num_experts=e, capacity=cap)
        np.testing.assert_allclose(slots, slots_ref, atol=1e-6)

        out = slots * 2.0 + 1.0  # any per-slot transform
        y_ref = gather_tokens(out, combine)
        y = gather_tokens_indexed(
            out, routing, num_experts=e, capacity=cap)
        np.testing.assert_allclose(y, y_ref, atol=1e-5)

    def test_fill_counts_match_onehot(self):
        from scaletorch_tpu.ops.pallas.grouped_mlp import slot_fill_counts
        from scaletorch_tpu.parallel.expert_parallel import (
            slot_fill_counts_indexed,
            top_k_routing_indexed,
        )

        logits, _ = self._problem()
        cap = expert_capacity(48, 8, 2, 0.5)
        dispatch, _, _ = top_k_routing(logits, 2, cap)
        routing, _ = top_k_routing_indexed(logits, 2, cap)
        np.testing.assert_array_equal(
            slot_fill_counts_indexed(routing, 8, cap),
            slot_fill_counts(dispatch),
        )

    @pytest.mark.slow
    def test_model_forward_matches_einsum_mode(self):
        import dataclasses

        cfg_e = dataclasses.replace(CFG, moe_dispatch="einsum")
        cfg_i = dataclasses.replace(CFG, moe_dispatch="index")
        params = init_params(jax.random.PRNGKey(0), cfg_e)
        ids = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                 CFG.vocab_size)
        np.testing.assert_allclose(
            forward(params, ids, cfg_i), forward(params, ids, cfg_e),
            atol=2e-5,
        )

    @pytest.mark.slow
    def test_grads_match_einsum_mode(self):
        import dataclasses

        cfg_e = dataclasses.replace(CFG, moe_dispatch="einsum",
                                    capacity_factor=0.75)  # with drops
        cfg_i = dataclasses.replace(cfg_e, moe_dispatch="index")
        params = init_params(jax.random.PRNGKey(0), cfg_e)
        ids = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                 CFG.vocab_size)

        def loss(p, cfg):
            logits, aux, _ = forward(p, ids, cfg, return_moe_stats=True)
            return jnp.mean(logits.astype(jnp.float32) ** 2) + aux

        g_e = jax.grad(loss)(params, cfg_e)
        g_i = jax.grad(loss)(params, cfg_i)
        for a, b in zip(jax.tree.leaves(g_e), jax.tree.leaves(g_i)):
            np.testing.assert_allclose(a, b, atol=2e-5)

    @pytest.mark.slow
    def test_ep2_matches_einsum_mode(self):
        import dataclasses

        cfg_e = dataclasses.replace(CFG, moe_dispatch="einsum")
        cfg_i = dataclasses.replace(CFG, moe_dispatch="index")
        params = init_params(jax.random.PRNGKey(0), cfg_e)
        ids = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                 CFG.vocab_size)
        mm = MeshManager(ep=2, dp=4)
        specs = qwen3_moe_param_specs(CFG, tp_axis="tp", ep_axis="ep")

        outs = {}
        for name, cfg in (("einsum", cfg_e), ("index", cfg_i)):
            def f(p, i, cfg=cfg):
                out = forward(p, i, cfg, ep_axis="ep")
                return jax.lax.pmean(out, ("ep", "tp"))

            outs[name] = jax.shard_map(
                f, mesh=mm.mesh, in_specs=(specs, P()), out_specs=P(),
            )(params, ids)
        np.testing.assert_allclose(outs["index"], outs["einsum"], atol=2e-5)

    def test_auto_resolution(self):
        import dataclasses

        # auto -> index at EVERY expert count: the einsum dispatch FLOPs
        # are E-independent (E*C = N*k*cf) and always the larger compile
        # (AOT_DISPATCH_CROSSOVER.json, swept E=4..64)
        assert CFG.resolved_moe_dispatch() == "index"  # E=8
        big = dataclasses.replace(CFG, num_experts=32)
        assert big.resolved_moe_dispatch() == "index"
        pinned = dataclasses.replace(CFG, moe_dispatch="einsum")
        assert pinned.resolved_moe_dispatch() == "einsum"
        with pytest.raises(ValueError, match="moe_dispatch"):
            dataclasses.replace(CFG, moe_dispatch="scatter")


MIX_CFG = Qwen3MoEConfig(
    vocab_size=128, hidden_size=32, intermediate_size=64,
    moe_intermediate_size=48, num_hidden_layers=4, num_attention_heads=4,
    num_key_value_heads=4, head_dim=8, num_experts=8, num_experts_per_tok=2,
    capacity_factor=8.0, dtype=jnp.float32, qk_norm=True,
    tie_word_embeddings=False,
    # sparse iff (i+1) % 2 == 0 and i != 2 -> layers 1, 3; dense 0, 2
    mlp_only_layers=(2,), decoder_sparse_step=2,
)


class TestInterleavedDense:
    """Interleaved dense/sparse Qwen3-MoE (HF mlp_only_layers /
    decoder_sparse_step — VERDICT r3 missing #3): segment-scan forward,
    gradients reach BOTH per-kind stacks, and the EPxTP SPMD step matches
    the single-device loss."""

    def _batch(self, accum=2, rows=4, seq=16):
        rng = np.random.default_rng(7)
        toks = rng.integers(0, MIX_CFG.vocab_size, (accum, rows, seq + 1))
        return {
            "input_ids": toks[:, :, :-1].astype(np.int32),
            "target_ids": toks[:, :, 1:].astype(np.int32),
            "position_ids": np.broadcast_to(
                np.arange(seq, dtype=np.int32), (accum, seq)
            ).copy(),
        }

    def test_param_stacks_follow_layout(self):
        params = init_params(jax.random.PRNGKey(0), MIX_CFG)
        layers = params["layers"]
        assert layers["q_proj"].shape[0] == 4          # all layers
        assert layers["router"].shape[0] == 2          # sparse subset
        assert layers["expert_gate_proj"].shape[:2] == (2, 8)
        assert layers["gate_proj"].shape == (2, 32, 64)  # dense subset

    @pytest.mark.slow
    def test_grads_reach_both_stacks(self):
        params = init_params(jax.random.PRNGKey(0), MIX_CFG)
        ids = jnp.asarray(self._batch()["input_ids"][0])

        def loss(p):
            logits, aux, _ = forward(p, ids, MIX_CFG, return_moe_stats=True)
            return jnp.mean(logits ** 2) + aux

        g = jax.grad(loss)(params)
        for key in ("gate_proj", "expert_gate_proj", "router", "q_proj"):
            assert float(jnp.max(jnp.abs(g["layers"][key]))) > 0, key

    @pytest.mark.slow
    def test_spmd_step_ep_tp_matches_single_device(self):
        from scaletorch_tpu.config import ScaleTorchTPUArguments
        from scaletorch_tpu.models.qwen3_moe import lm_head_weight
        from scaletorch_tpu.parallel.spmd import make_spmd_train_step, shard_params
        from scaletorch_tpu.parallel.tensor_parallel import (
            fused_vocab_parallel_cross_entropy,
        )
        from scaletorch_tpu.trainer.optimizer import create_optimizer

        params = init_params(jax.random.PRNGKey(0), MIX_CFG)
        batch = self._batch()
        seq = batch["input_ids"].shape[-1]
        pos = jnp.arange(seq, dtype=jnp.int32)

        def ref_loss(p):
            losses = []
            for m in range(batch["input_ids"].shape[0]):
                hidden, aux = forward(
                    p, jnp.asarray(batch["input_ids"][m]), MIX_CFG,
                    positions=pos, return_hidden=True)
                head = lm_head_weight(p, MIX_CFG, None)
                ce = fused_vocab_parallel_cross_entropy(
                    hidden, head, jnp.asarray(batch["target_ids"][m]),
                    axis=None)
                losses.append(ce + aux)
            return sum(losses) / len(losses)

        tcfg = ScaleTorchTPUArguments(
            learning_rate=1e-2, total_train_steps=10, warmup_steps=0,
        )
        tx, _ = create_optimizer(tcfg, include_clip=False)
        mm = MeshManager(ep=2, tp=2, dp=2)
        specs = qwen3_moe_param_specs(MIX_CFG, tp_axis="tp", ep_axis="ep")
        step_fn, p_specs, o_specs = make_spmd_train_step(
            mm, forward, MIX_CFG, tx, params,
            donate=False, param_specs=specs,
            model_kwargs={"ep_axis": "ep", "return_moe_stats": True},
            model_family="qwen3_moe",
        )
        p2, _, metrics = step_fn(
            shard_params(mm, params, p_specs),
            shard_params(mm, tx.init(params), o_specs),
            batch,
        )
        assert float(metrics["loss"]) == pytest.approx(
            float(ref_loss(params)), rel=1e-5
        )
        assert 0.0 <= float(metrics["moe_dropped_fraction"]) <= 1.0
        delta = jax.tree.map(
            lambda a, b: float(jnp.max(jnp.abs(jnp.asarray(a) - b))),
            jax.device_get(p2), params,
        )
        assert max(jax.tree.leaves(delta)) > 0

    def test_pp_composition_rejected(self):
        with pytest.raises(NotImplementedError, match="pp=1"):
            qwen3_moe_param_specs(MIX_CFG, tp_axis="tp", ep_axis="ep",
                                  pp_axis="pp")


@pytest.mark.slow
class TestMoEPipeline:
    """PP x EP composition (VERDICT r1 missing #8): the MoE pipeline loss
    and one-step update must match the single-device MoE step."""

    def _batch(self, accum=2, rows=4, seq=16):
        rng = np.random.default_rng(0)
        toks = rng.integers(0, CFG.vocab_size, (accum, rows, seq + 1))
        return {
            "input_ids": toks[:, :, :-1].astype(np.int32),
            "target_ids": toks[:, :, 1:].astype(np.int32),
            "position_ids": np.broadcast_to(
                np.arange(seq, dtype=np.int32), (accum, seq)
            ).copy(),
        }

    def _ref_loss(self, params, batch):
        """Single-device mean over microbatches of (CE + aux) — the SPMD
        step's exact loss form."""
        from scaletorch_tpu.models.qwen3_moe import lm_head_weight
        from scaletorch_tpu.parallel.tensor_parallel import (
            fused_vocab_parallel_cross_entropy,
        )

        seq = batch["input_ids"].shape[-1]
        pos = jnp.arange(seq, dtype=jnp.int32)

        def one(p, ids, tgt):
            hidden, aux = forward(p, ids, CFG, positions=pos,
                                  return_hidden=True)
            head = lm_head_weight(p, CFG, None)
            ce = fused_vocab_parallel_cross_entropy(hidden, head, tgt,
                                                    axis=None)
            return ce + aux

        def loss(p):
            losses = [
                one(p, jnp.asarray(batch["input_ids"][m]),
                    jnp.asarray(batch["target_ids"][m]))
                for m in range(batch["input_ids"].shape[0])
            ]
            return sum(losses) / len(losses)

        return loss

    def test_pp_ep_update_matches_single_device(self):
        import optax

        from scaletorch_tpu.config import ScaleTorchTPUArguments
        from scaletorch_tpu.parallel.spmd import make_spmd_train_step, shard_params
        from scaletorch_tpu.trainer.optimizer import create_optimizer

        params = init_params(jax.random.PRNGKey(0), CFG)
        batch = self._batch()
        ref_loss = self._ref_loss(params, batch)

        tcfg = ScaleTorchTPUArguments(
            learning_rate=1e-2, total_train_steps=10, warmup_steps=0,
            optimizer_name="sgd",
        )
        tx, _ = create_optimizer(tcfg, include_clip=False)
        grads_ref = jax.grad(ref_loss)(params)
        updates, _ = tx.update(grads_ref, tx.init(params), params)
        p_ref = optax.apply_updates(params, updates)

        mm = MeshManager(pp=2, ep=2, dp=2)
        specs = qwen3_moe_param_specs(CFG, tp_axis="tp", ep_axis="ep",
                                      pp_axis="pp")
        step_fn, p_specs, o_specs = make_spmd_train_step(
            mm, forward, CFG, tx, params,
            donate=False, param_specs=specs,
            model_kwargs={"ep_axis": "ep"},
            model_family="qwen3_moe", pp_schedule="afab",
        )
        p2, _, metrics = step_fn(
            shard_params(mm, params, p_specs),
            shard_params(mm, tx.init(params), o_specs),
            batch,
        )
        assert float(metrics["loss"]) == pytest.approx(
            float(ref_loss(params)), rel=1e-5
        )
        # routing health stats flow through the pipeline too
        assert 0.0 <= float(metrics["moe_dropped_fraction"]) <= 1.0
        assert float(metrics["moe_load_cv"]) >= 0.0
        for a, b in zip(jax.tree.leaves(p_ref),
                        jax.tree.leaves(jax.device_get(p2))):
            np.testing.assert_allclose(a, b, atol=3e-5)

    @pytest.mark.parametrize("schedule", ["afab", "1f1b"])
    def test_spmd_step_pp_ep_tp(self, schedule):
        from scaletorch_tpu.config import ScaleTorchTPUArguments
        from scaletorch_tpu.parallel.spmd import make_spmd_train_step, shard_params
        from scaletorch_tpu.trainer.optimizer import create_optimizer

        mm = MeshManager(pp=2, ep=2, tp=2)
        params = init_params(jax.random.PRNGKey(0), CFG)
        tcfg = ScaleTorchTPUArguments(
            learning_rate=1e-3, total_train_steps=10, warmup_steps=0
        )
        tx, _ = create_optimizer(tcfg, include_clip=False)
        specs = qwen3_moe_param_specs(CFG, tp_axis="tp", ep_axis="ep",
                                      pp_axis="pp")
        step_fn, p_specs, o_specs = make_spmd_train_step(
            mm, forward, CFG, tx, params,
            max_grad_norm=1.0, donate=False, param_specs=specs,
            model_kwargs={"ep_axis": "ep"},
            model_family="qwen3_moe", pp_schedule=schedule,
        )
        batch = self._batch(accum=2, rows=2)
        p2, o2, metrics = step_fn(
            shard_params(mm, params, p_specs),
            shard_params(mm, tx.init(params), o_specs),
            batch,
        )
        assert np.isfinite(float(metrics["loss"]))
        assert np.isfinite(float(metrics["grad_norm"]))
        assert 0.0 <= float(metrics["moe_dropped_fraction"]) <= 1.0
        delta = jax.tree.map(
            lambda a, b: float(jnp.max(jnp.abs(jnp.asarray(a) - b))), p2, params
        )
        assert max(jax.tree.leaves(delta)) > 0


@pytest.mark.slow
class TestSortBasedDispatch:
    """The reference's ragged sort-based exchange (ep_comms.py:41-133) as
    a jittable equal-slab all_to_all: zero token drops even under routing
    skew that makes the capacity path drop."""

    def _problem(self, seed=0, n=64, e=8, k=2, h=16, i=32):
        rng = np.random.default_rng(seed)
        x = rng.standard_normal((n, h)).astype(np.float32)
        w = [rng.standard_normal(s).astype(np.float32) * 0.1
             for s in ((e, h, i), (e, h, i), (e, i, h))]
        # deliberately imbalanced routing: most mass on experts 0-1
        p = np.array([.4, .3, .1, .05, .05, .04, .03, .03])
        gate_idx = rng.choice(e, size=(n, k), p=p).astype(np.int32)
        gate_w = rng.random((n, k)).astype(np.float32)
        return x, gate_idx, gate_w, w

    def _dense_reference(self, x, gate_idx, gate_w, w):
        from scaletorch_tpu.models.layers import swiglu

        gp, up, dn = w
        ref = np.zeros_like(x)
        for n_ in range(x.shape[0]):
            for j in range(gate_idx.shape[1]):
                e = gate_idx[n_, j]
                t = x[n_]
                o = np.asarray(
                    swiglu(jnp.asarray(t @ gp[e]), jnp.asarray(t @ up[e]))
                ) @ dn[e]
                ref[n_] += gate_w[n_, j] * o
        return ref

    def test_single_rank_noop_contract(self):
        from scaletorch_tpu.parallel.expert_parallel import sorted_moe_forward

        x, gi, gw, w = self._problem()
        out = sorted_moe_forward(
            jnp.asarray(x), jnp.asarray(gi), jnp.asarray(gw), *map(jnp.asarray, w),
            axis=None, num_experts=8)
        np.testing.assert_allclose(out, self._dense_reference(x, gi, gw, w),
                                   atol=1e-4)

    @pytest.mark.parametrize("ep", [2, 4])
    def test_zero_drop_exactness_under_skew(self, ep):
        from scaletorch_tpu.parallel.expert_parallel import sorted_moe_forward

        x, gi, gw, w = self._problem()
        ref = self._dense_reference(x, gi, gw, w)
        mm = MeshManager(ep=ep, dp=8 // ep)

        def f(x, gi, gw, g, u, d):
            return sorted_moe_forward(x, gi, gw, g, u, d, axis="ep",
                                      num_experts=8)

        out = jax.shard_map(
            f, mesh=mm.mesh, in_specs=(P("ep"),) * 6, out_specs=P("ep"),
        )(x, gi, gw, *w)
        np.testing.assert_allclose(np.asarray(out), ref, atol=1e-4)

    def test_dispatch_invariants(self):
        """Reference test_ep_comms.py:69-96 parity: sizes sum to N,
        received ids are in the local range, round-trip restores order."""
        from scaletorch_tpu.parallel.expert_parallel import (
            sort_dispatch_tokens,
            sort_gather_tokens,
        )

        x, gi, _, _ = self._problem()
        n, h = x.shape
        flat_x = np.repeat(x, 2, axis=0)
        flat_ids = gi.reshape(-1)
        mm = MeshManager(ep=4, dp=2)

        def f(x, ids):
            recv, local_ids, valid, meta = sort_dispatch_tokens(
                x, ids, axis="ep", num_experts=8)
            e_local = 2
            ok_range = jnp.all(
                jnp.where(valid, (local_ids >= 0) & (local_ids < e_local), True))
            # round-trip: identity compute must restore the input rows
            back = sort_gather_tokens(recv, meta, axis="ep")
            n_recv = jnp.sum(valid)
            return back, ok_range[None], n_recv[None]

        back, ok_range, n_recv = jax.shard_map(
            f, mesh=mm.mesh, in_specs=(P("ep"), P("ep")),
            out_specs=(P("ep"), P("ep"), P("ep")),
        )(flat_x, flat_ids)
        assert np.all(np.asarray(ok_range))
        # every (token, choice) row was exchanged exactly once globally
        assert int(np.sum(np.asarray(n_recv))) == flat_x.shape[0] * 4 // 4
        np.testing.assert_allclose(np.asarray(back), flat_x, atol=0)

    def test_gradients_flow_through_exchange(self):
        from scaletorch_tpu.parallel.expert_parallel import sorted_moe_forward

        x, gi, gw, w = self._problem(n=32)
        mm = MeshManager(ep=2, dp=4)

        def loss_sharded(x, gi, gw, g, u, d):
            out = sorted_moe_forward(x, gi, gw, g, u, d, axis="ep",
                                     num_experts=8)
            return jax.lax.psum(jnp.sum(out ** 2), "ep")

        def loss_ref(x, g, u, d):
            out = sorted_moe_forward(
                jnp.asarray(x), jnp.asarray(gi), jnp.asarray(gw), g, u, d,
                axis=None, num_experts=8)
            return jnp.sum(out ** 2)

        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(
            jnp.asarray(x), *map(jnp.asarray, w))
        g = jax.shard_map(
            lambda *a: jax.grad(loss_sharded, argnums=(0, 3, 4, 5))(*a),
            mesh=mm.mesh, in_specs=(P("ep"),) * 6,
            out_specs=(P("ep"),) * 4,
        )(x, gi, gw, *w)
        for a, b in zip(g_ref, g):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-4)

    def test_chunk_capacity_overflow_drops_to_zero(self):
        """Rows past a destination slab must come back as ZEROS (token
        dropped), never as a clamped-gather copy of another row's output."""
        from scaletorch_tpu.parallel.expert_parallel import (
            sort_dispatch_tokens,
            sort_gather_tokens,
        )

        mm = MeshManager(ep=2, dp=4)
        n, h, cap = 8, 4, 3
        x = np.arange(n * h, dtype=np.float32).reshape(n, h) + 1.0
        ids = np.zeros(n, np.int32)  # every row to expert 0 -> rank 0

        def f(x, ids):
            recv, _, valid, meta = sort_dispatch_tokens(
                x, ids, axis="ep", num_experts=2, chunk_capacity=cap)
            return sort_gather_tokens(recv, meta, axis="ep")

        back = np.asarray(jax.shard_map(
            f, mesh=mm.mesh, in_specs=(P("ep"), P("ep")), out_specs=P("ep"),
        )(x, ids))
        kept, dropped = back[:cap], back[cap:4]
        np.testing.assert_allclose(kept, x[:cap])
        assert (dropped == 0).all(), dropped

    def test_overflow_drop_count_is_observable(self):
        """meta['dropped_rows'] reports skew-induced drops (ADVICE r3):
        zero on the default zero-drop capacity, exact count otherwise."""
        from scaletorch_tpu.parallel.expert_parallel import (
            sort_dispatch_tokens,
        )

        mm = MeshManager(ep=2, dp=4)
        n, h = 8, 4
        x = np.ones((n, h), np.float32)
        ids = np.zeros(n, np.int32)  # all 4 local rows -> rank 0's slab

        def f(x, ids, cap):
            *_, meta = sort_dispatch_tokens(
                x, ids, axis="ep", num_experts=2, chunk_capacity=cap)
            return meta["dropped_rows"][None]

        for cap, want in ((None, 0), (3, 1), (1, 3)):
            got = np.asarray(jax.shard_map(
                lambda a, b: f(a, b, cap), mesh=mm.mesh,
                in_specs=(P("ep"), P("ep")), out_specs=P("ep"),
            )(x, ids))
            # every ep rank sends its whole 4-row shard to rank 0
            assert (got == want).all(), (cap, got)

    def test_high_e_local_warns(self):
        """The sort path's masked compute scales E_local-x; enabling it at
        high local expert counts must not be silent (VERDICT r3 weak #5)."""
        from scaletorch_tpu.parallel.expert_parallel import sorted_moe_forward

        x, gi, gw, w = self._problem()  # e=8, axis=None -> E_local=8
        with pytest.warns(RuntimeWarning, match="E_local=8"):
            sorted_moe_forward(
                jnp.asarray(x), jnp.asarray(gi), jnp.asarray(gw),
                *map(jnp.asarray, w), axis=None, num_experts=8)
