"""FSDP (GSPMD param/state sharding, parallel/fsdp.py) correctness.

Mirrors the reference's FSDP2 smoke tests (examples/FSDP2/test_smoke.py
role): sharded training must match replicated training numerically, and
the storage must actually be sharded.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scaletorch_tpu.config import ScaleTorchTPUArguments
from scaletorch_tpu.models.llama import LlamaConfig, forward, init_params
from scaletorch_tpu.parallel.fsdp import (
    fsdp_param_specs,
    setup_fsdp,
)
from scaletorch_tpu.trainer.optimizer import create_optimizer
from scaletorch_tpu.trainer.train_step import make_train_step

CFG = LlamaConfig(
    vocab_size=256,
    hidden_size=64,
    intermediate_size=128,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    head_dim=16,
    max_position_embeddings=64,
    dtype=jnp.float32,
)


def _batch(accum=1, rows=8, seq=16, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, CFG.vocab_size, (accum, rows, seq + 1))
    return {
        "input_ids": jnp.asarray(ids[:, :, :-1], jnp.int32),
        "target_ids": jnp.asarray(ids[:, :, 1:], jnp.int32),
    }


def _tx():
    args = ScaleTorchTPUArguments(
        total_train_steps=10, learning_rate=1e-3, warmup_steps=0,
    )
    return create_optimizer(args, include_clip=False)[0]


class TestSpecs:
    def test_largest_divisible_dim(self):
        params = {
            "w": jnp.zeros((2, 64, 128)),   # largest dim 128 -> sharded
            "emb": jnp.zeros((250, 64)),    # 250 % 8 != 0, 64 % 8 == 0
            "norm": jnp.zeros((7,)),        # nothing divisible
        }
        specs = fsdp_param_specs(params, 8)
        assert specs["w"].index("fsdp") == 2
        assert specs["emb"].index("fsdp") == 1
        assert "fsdp" not in tuple(specs["norm"])


class TestFsdpTraining:
    @pytest.mark.slow
    def test_matches_replicated_and_shards_storage(self):
        params_host = init_params(jax.random.key(0), CFG)

        # replicated baseline (plain jit, no mesh)
        tx = _tx()
        base_step = make_train_step(forward, CFG, tx, donate=False)
        p_ref = jax.tree.map(jnp.copy, params_host)
        o_ref = tx.init(p_ref)
        losses_ref = []
        for i in range(3):
            p_ref, o_ref, m = base_step(p_ref, o_ref, _batch(seed=i))
            losses_ref.append(float(m["loss"]))

        # FSDP over all 8 virtual devices
        tx2 = _tx()
        step_fn, p_sh, o_sh, mesh = setup_fsdp(
            forward, CFG, params_host, tx2, donate=False
        )
        n_dev = mesh.shape["fsdp"]
        assert n_dev == 8
        losses = []
        for i in range(3):
            p_sh, o_sh, m = step_fn(p_sh, o_sh, _batch(seed=i))
            losses.append(float(m["loss"]))

        np.testing.assert_allclose(losses, losses_ref, rtol=2e-4)

        # storage really is sharded: big leaves hold 1/8 per device, and
        # the optimizer state inherited the sharding (ZeRO-1 on top)
        def shard_frac(x):
            return x.addressable_shards[0].data.size / x.size

        big_param_fracs = [
            shard_frac(p) for p in jax.tree.leaves(p_sh) if p.size >= 4096
        ]
        assert big_param_fracs and max(big_param_fracs) <= 1 / n_dev + 1e-9
        big_state_fracs = [
            shard_frac(s) for s in jax.tree.leaves(o_sh) if s.size >= 4096
        ]
        assert big_state_fracs and max(big_state_fracs) <= 1 / n_dev + 1e-9

    @pytest.mark.slow
    def test_bf16_params_supported(self):
        cfg16 = LlamaConfig(**{**CFG.__dict__, "dtype": jnp.bfloat16,
                               "param_dtype": jnp.bfloat16})
        params_host = init_params(jax.random.key(1), cfg16)
        tx = _tx()
        step_fn, p_sh, o_sh, _ = setup_fsdp(
            forward, cfg16, params_host, tx, donate=False
        )
        p_sh, o_sh, m = step_fn(p_sh, o_sh, _batch(seed=3))
        assert np.isfinite(float(m["loss"]))
        assert all(
            p.dtype == jnp.bfloat16 for p in jax.tree.leaves(p_sh)
        )
