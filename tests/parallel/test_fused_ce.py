"""Fused chunked vocab-parallel CE must match the unfused one (which is
itself golden-tested against dense softmax CE in test_tensor_parallel)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from scaletorch_tpu.parallel.tensor_parallel import (
    fused_vocab_parallel_cross_entropy,
    vocab_parallel_cross_entropy,
)


def _setup(mm_factory, vocab=64, b=2, s=16, h=8):
    mm = mm_factory(tp=8)
    key = jax.random.key(0)
    kx, kh, kt = jax.random.split(key, 3)
    hidden = jax.random.normal(kx, (b, s, h), jnp.float32)
    head = jax.random.normal(kh, (h, vocab), jnp.float32)
    targets = jax.random.randint(kt, (b, s), 0, vocab)
    targets = targets.at[0, 0].set(-100)  # exercise ignore_index
    return mm, hidden, head, targets


def test_fused_matches_unfused(mm_factory):
    mm, hidden, head, targets = _setup(mm_factory)

    def fused(hd, hw, t):
        return fused_vocab_parallel_cross_entropy(hd, hw, t, axis="tp",
                                                  chunk_size=4)

    def unfused(hd, hw, t):
        return vocab_parallel_cross_entropy(hd @ hw, t, axis="tp")

    specs = (P(), P(None, "tp"), P())
    run_fused = jax.jit(jax.shard_map(fused, mesh=mm.mesh, in_specs=specs,
                                      out_specs=P()))
    run_unfused = jax.jit(jax.shard_map(unfused, mesh=mm.mesh, in_specs=specs,
                                        out_specs=P()))
    np.testing.assert_allclose(
        run_fused(hidden, head, targets), run_unfused(hidden, head, targets),
        rtol=1e-5,
    )


def test_fused_gradients_match(mm_factory):
    mm, hidden, head, targets = _setup(mm_factory)
    specs = (P(), P(None, "tp"), P())

    def g(fn):
        def wrapped(hd, hw, t):
            return jax.grad(fn, argnums=(0, 1))(hd, hw, t)
        return jax.jit(jax.shard_map(wrapped, mesh=mm.mesh, in_specs=specs,
                                     out_specs=(P(), P(None, "tp"))))

    gf = g(lambda hd, hw, t: fused_vocab_parallel_cross_entropy(
        hd, hw, t, axis="tp", chunk_size=4))
    gu = g(lambda hd, hw, t: vocab_parallel_cross_entropy(hd @ hw, t, axis="tp"))
    for a, b in zip(gf(hidden, head, targets), gu(hidden, head, targets)):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6)


@pytest.mark.slow
def test_fused_no_tp_axis():
    """axis=None path (single-device semantics, no collectives)."""
    key = jax.random.key(1)
    kx, kh, kt = jax.random.split(key, 3)
    hidden = jax.random.normal(kx, (2, 8, 8), jnp.float32)
    head = jax.random.normal(kh, (8, 32), jnp.float32)
    targets = jax.random.randint(kt, (2, 8), 0, 32)
    got = fused_vocab_parallel_cross_entropy(hidden, head, targets, axis=None,
                                             chunk_size=4)
    logits = (hidden @ head).astype(jnp.float32)
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    np.testing.assert_allclose(got, jnp.mean(logz - gold), rtol=1e-5)
