"""MeshManager grid math — parity with reference process_group tests."""

import jax
import pytest

from scaletorch_tpu.parallel.mesh import (
    MESH_AXES,
    MeshCoords,
    MeshManager,
    mesh_manager,
    reset_mesh_manager,
    setup_mesh_manager,
)


class TestGridMath:
    def test_world_size_validation(self):
        with pytest.raises(ValueError, match="device count"):
            MeshManager(tp=4, dp=4)  # 16 > 8 devices

    def test_dim_validation(self):
        with pytest.raises(ValueError, match=">= 1"):
            MeshManager(tp=0)

    def test_rank_decomposition_tp_fastest(self):
        # Reference order: TP fastest -> EP -> CP -> PP -> DP
        # (process_group.py:94-102).
        mm = MeshManager(tp=2, cp=2, dp=2)
        assert mm.coords(0) == MeshCoords(dp=0, pp=0, cp=0, ep=0, tp=0)
        assert mm.coords(1) == MeshCoords(dp=0, pp=0, cp=0, ep=0, tp=1)
        assert mm.coords(2) == MeshCoords(dp=0, pp=0, cp=1, ep=0, tp=0)
        assert mm.coords(4) == MeshCoords(dp=1, pp=0, cp=0, ep=0, tp=0)
        assert mm.coords(7) == MeshCoords(dp=1, pp=0, cp=1, ep=0, tp=1)

    def test_rank_roundtrip_all_geometries(self):
        for dims in [(2, 2, 2, 1, 1), (8, 1, 1, 1, 1), (1, 2, 1, 2, 2), (1, 1, 1, 1, 8)]:
            dp, pp, cp, ep, tp = dims
            mm = MeshManager(dp=dp, pp=pp, cp=cp, ep=ep, tp=tp)
            for r in range(mm.world_size):
                assert mm.rank_of(mm.coords(r)) == r

    def test_rank_out_of_range(self):
        mm = MeshManager(tp=8)
        with pytest.raises(ValueError, match="out of range"):
            mm.coords(8)

    def test_mesh_axes_and_shape(self):
        mm = MeshManager(dp=2, cp=2, tp=2)
        assert mm.mesh.axis_names == MESH_AXES
        assert mm.shape == (2, 1, 2, 1, 2)
        assert mm.axis_size("cp") == 2
        assert mm.world_size == 8

    def test_explicit_devices_honour_caller_order(self):
        """With an explicit device list, mesh.devices[coords] is
        devices[logical_rank] (row-major, tp fastest). The devices=None path
        may reorder for ICI topology — only the explicit path promises this."""
        mm = MeshManager(dp=2, cp=2, tp=2, devices=jax.devices())
        for r in range(8):
            assert mm.device_at(mm.coords(r)) == jax.devices()[r]


class TestNeighbours:
    def test_cp_ring(self):
        mm = MeshManager(cp=4, dp=2)
        assert mm.cp_send_rank(0) == 1
        assert mm.cp_send_rank(3) == 0
        assert mm.cp_recv_rank(0) == 3
        assert mm.cp_ring_permutation() == [(0, 1), (1, 2), (2, 3), (3, 0)]

    def test_pp_chain(self):
        mm = MeshManager(pp=4, tp=2)
        assert mm.pp_prev_rank(0) is None
        assert mm.pp_next_rank(3) is None
        assert mm.pp_next_rank(1) == 2
        assert mm.pp_is_first_stage(0) and not mm.pp_is_first_stage(1)
        assert mm.pp_is_last_stage(3) and not mm.pp_is_last_stage(2)
        assert mm.pp_fwd_permutation() == [(0, 1), (1, 2), (2, 3)]
        assert mm.pp_bwd_permutation() == [(1, 0), (2, 1), (3, 2)]


class TestSingleton:
    def test_proxy_unset_raises(self):
        reset_mesh_manager()
        assert not mesh_manager
        with pytest.raises(RuntimeError, match="not initialised"):
            _ = mesh_manager.world_size

    def test_proxy_after_setup(self):
        setup_mesh_manager(tp=2, dp=4)
        assert mesh_manager
        assert mesh_manager.world_size == 8
        assert mesh_manager.tp == 2


class TestCollectivesOnMesh:
    """Real collectives over the virtual 8-device mesh (not mocks)."""

    def test_psum_over_tp(self):
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        mm = MeshManager(tp=8)
        f = jax.shard_map(
            lambda x: jax.lax.psum(x, "tp"),
            mesh=mm.mesh,
            in_specs=P(None, None, None, None, "tp"),
            out_specs=P(None, None, None, None, "tp"),
        )
        x = jnp.ones((1, 1, 1, 1, 8))
        assert (f(x) == 8).all()

    def test_ppermute_ring_over_cp(self):
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import PartitionSpec as P

        mm = MeshManager(cp=4, dp=2)

        def shift(x):
            return jax.lax.ppermute(x, "cp", perm=mm.cp_ring_permutation())

        f = jax.shard_map(
            lambda x: shift(x),
            mesh=mm.mesh,
            in_specs=P(None, None, "cp"),
            out_specs=P(None, None, "cp"),
        )
        x = jnp.arange(4.0).reshape(1, 1, 4)
        out = f(x)
        np.testing.assert_allclose(np.asarray(out)[0, 0], [3.0, 0.0, 1.0, 2.0])
