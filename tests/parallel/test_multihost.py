"""Multi-host runtime test: 2 real processes x 4 virtual CPU devices.

The reference smoke-tests its NCCL/HCCL bootstrap by launching torchrun
jobs (scripts/torch_dist/); here the equivalent attestation is strictly
stronger and runs inside pytest: two OS processes form a gloo-backed
jax.distributed cluster (scaletorch_tpu/dist.py) whose 8 global devices
train the SAME tiny llama config as the single-process 8-device path, and
the losses must agree step for step.

Covers: infer_launcher env discovery (torchrun-style MASTER_ADDR/RANK/
WORLD_SIZE names), init_distributed via the Trainer, put_global feeding
(every process contributes only its addressable shards), and replicated
metrics readout.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys

import pytest

from scaletorch_tpu.dist import infer_launcher

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

TRAIN_ARGS = [
    "--model_type", "llama",
    "--hidden_size", "64",
    "--intermediate_size", "128",
    "--num_hidden_layers", "2",
    "--num_attention_heads", "4",
    "--vocab_size", "128",
    "--sequence_length", "32",
    "--max_position_embeddings", "64",
    "--data_parallel_size", "4",
    "--tensor_parallel_size", "2",
    "--micro_batch_size", "2",
    "--gradient_accumulation_steps", "2",
    "--synthetic_data", "true",
    "--total_train_steps", "3",
    "--dtype", "float32",
    "--max_grad_norm", "1.0",
    "--donate_params", "false",
    "--log_frequency", "1",
]

WORKER = r"""
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.environ["ST_REPO"])
from scaletorch_tpu.config import parse_args
from scaletorch_tpu.trainer.trainer import Trainer

cfg = parse_args(json.loads(os.environ["ST_ARGS"]))
trainer = Trainer(cfg)
assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 8, jax.device_count()
losses = []
it = iter(trainer.loader)
for _ in range(cfg.total_train_steps):
    batch = trainer._device_batch(next(it))
    trainer.params, trainer.opt_state, m = trainer.step_fn(
        trainer.params, trainer.opt_state, batch
    )
    losses.append(float(m["loss"]))

# object collectives over the real 2-process cluster (reference
# object_ops/gather_utils parity): arbitrary picklables, uneven sizes
from scaletorch_tpu.dist import all_gather_object, collect_results
me = jax.process_index()
mine = {"proc": me, "payload": "x" * (10 + 100 * me), "nested": [me, {me: me}]}
gathered = all_gather_object(mine)
assert [g["proc"] for g in gathered] == [0, 1], gathered
part = [f"s{me}", f"s{me + 2}"]  # round-robin shard of ['s0','s1','s2','s3']
merged = collect_results(part, size=3)
if me == 0:
    assert merged == ["s0", "s1", "s2"], merged
else:
    assert merged is None, merged

print("RESULT " + json.dumps({"proc": jax.process_index(), "losses": losses,
                              "objects_ok": True}),
      flush=True)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _single_process_losses(n_steps: int):
    """Ground truth: same config on this process's 8 virtual devices."""
    from scaletorch_tpu.config import parse_args
    from scaletorch_tpu.trainer.trainer import Trainer

    cfg = parse_args(TRAIN_ARGS)
    trainer = Trainer(cfg)
    losses = []
    it = iter(trainer.loader)
    for _ in range(n_steps):
        batch = trainer._device_batch(next(it))
        trainer.params, trainer.opt_state, m = trainer.step_fn(
            trainer.params, trainer.opt_state, batch
        )
        losses.append(float(m["loss"]))
    return losses


def test_infer_launcher_env_styles(monkeypatch):
    for var in ("MASTER_ADDR", "WORLD_SIZE", "RANK", "SLURM_NTASKS",
                "OMPI_COMM_WORLD_SIZE", "JAX_COORDINATOR_ADDRESS",
                "JAX_NUM_PROCESSES"):
        monkeypatch.delenv(var, raising=False)
    assert infer_launcher() == "none"
    monkeypatch.setenv("SLURM_NTASKS", "4")
    assert infer_launcher() == "slurm"
    monkeypatch.setenv("OMPI_COMM_WORLD_SIZE", "4")
    assert infer_launcher() == "slurm"  # slurm checked first, as reference
    monkeypatch.delenv("SLURM_NTASKS")
    assert infer_launcher() == "mpi"
    monkeypatch.setenv("MASTER_ADDR", "10.0.0.1")
    assert infer_launcher() == "env"  # explicit env beats scheduler vars
    monkeypatch.delenv("MASTER_ADDR")
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.1:1234")
    assert infer_launcher() == "env"
    # A bare WORLD_SIZE without a coordinator address (stale torchrun /
    # SageMaker ambience) must stay single-process, not error out.
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS")
    monkeypatch.delenv("OMPI_COMM_WORLD_SIZE")
    monkeypatch.setenv("WORLD_SIZE", "8")
    assert infer_launcher() == "none"


@pytest.mark.slow
def test_two_process_training_matches_single_process(tmp_path):
    port = _free_port()
    worker_py = tmp_path / "worker.py"
    worker_py.write_text(WORKER)

    procs = []
    for rank in range(2):
        env = {k: v for k, v in os.environ.items()
               if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
        # torchrun-style names on purpose: exercises the compat aliasing.
        env.update(
            MASTER_ADDR="127.0.0.1",
            MASTER_PORT=str(port),
            WORLD_SIZE="2",
            RANK=str(rank),
            ST_REPO=REPO,
            ST_ARGS=json.dumps(TRAIN_ARGS),
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, str(worker_py)],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    outs = [p.communicate(timeout=600)[0] for p in procs]
    results = {}
    for out, p in zip(outs, procs):
        assert p.returncode == 0, f"worker failed:\n{out[-3000:]}"
        line = [ln for ln in out.splitlines() if ln.startswith("RESULT ")]
        assert line, f"no RESULT line in:\n{out[-3000:]}"
        r = json.loads(line[-1][len("RESULT "):])
        results[r["proc"]] = r["losses"]

    assert set(results) == {0, 1}
    # Both processes see the identical replicated global loss...
    assert results[0] == pytest.approx(results[1], rel=1e-6)
    # ...and it matches the single-process 8-device ground truth.
    expected = _single_process_losses(len(results[0]))
    assert results[0] == pytest.approx(expected, rel=2e-4)
