"""Interleaved (virtual-stage) pipeline engine.

The reference's production schedule is interleaved 1F1B
(pipeline_parallel.py:457-671): each rank holds several non-contiguous
model chunks so the pipeline bubble shrinks by the chunk count. Here the
SPMD re-design (circular ppermute ring, vpp laps) is tested three ways:

  * the static tick schedule against a discrete-event simulator built
    independently from first principles (no shared index math);
  * the param re-blocking (interleave/deinterleave) as an exact
    permutation roundtrip;
  * full numerics — loss AND grads — against the single-device golden,
    including TP composition, partial cohorts (M % pp != 0), the MoE
    variant with aux/stats, and the spmd train step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from scaletorch_tpu.models.llama import LlamaConfig, forward, init_params
from scaletorch_tpu.parallel.mesh import MeshManager
from scaletorch_tpu.parallel.pipeline_parallel import (
    deinterleave_stacked_params,
    interleave_stacked_params,
    interleaved_finish_ticks,
    interleaved_tick_schedule,
    make_llama_pipeline_loss,
    validate_interleaved_divisibility,
)

# 8 layers: divisible by every pp*vpp factoring under test (2*2, 4*2, 2*4)
CFG = LlamaConfig(
    vocab_size=128, hidden_size=32, intermediate_size=64, num_hidden_layers=8,
    num_attention_heads=4, num_key_value_heads=4, dtype=jnp.float32,
)


def simulate_schedule(m, pp, vpp):
    """Independent discrete-event simulation of the circular pipeline:
    microbatches enter rank 0 in cohorts of pp and advance one virtual
    stage per tick around the wrap ring. Returns (per-tick occupancy
    {(tick, rank): (mb, vstage)}, finish tick per mb)."""
    occupancy = {}
    finish = [None] * m
    # (mb, next_vstage) currently held by each rank, None = empty
    held = [None] * pp
    pending = list(range(m))
    t = 0
    while any(h is not None for h in held) or pending:
        # ring advance: rank r's completed item moves to (r+1) % pp
        new_held = [None] * pp
        for r in range(pp):
            if held[r] is not None:
                mb, vs = held[r]
                if vs + 1 < pp * vpp:
                    new_held[(r + 1) % pp] = (mb, vs + 1)
                # else: finished, leaves the ring
        held = new_held
        # injection at rank 0 on the cohort cadence (t mod (pp*vpp) < pp);
        # the design claims the slot is always free then — assert it, so a
        # collision in the schedule fails loudly here
        if pending and t % (pp * vpp) < pp:
            assert held[0] is None, f"injection collision at tick {t}"
            held[0] = (pending.pop(0), 0)
        for r in range(pp):
            if held[r] is not None:
                mb, vs = held[r]
                assert vs % pp == r, "vstage must live on rank vs % pp"
                occupancy[(t, r)] = (mb, vs)
                if vs == pp * vpp - 1:
                    finish[mb] = t
        t += 1
        if t > 10_000:
            raise RuntimeError("simulator did not drain")
    return occupancy, finish


def expected_occupancy(t, r, m, pp, vpp):
    """The traced tick loop's index math, in one place for both the
    hand-picked and randomized simulator cross-checks: (mb, vstage) live
    at (tick, rank), or None."""
    period = pp * vpp
    u = t - r
    u_c = max(u, 0)
    w = u_c % period
    c = w // pp
    mb = (u_c // period) * pp + (w % pp)
    if u >= 0 and mb < m:
        return (mb, c * pp + r)
    return None


class TestSchedule:
    @pytest.mark.parametrize("m,pp,vpp", [
        (2, 2, 2), (4, 2, 2), (8, 4, 2), (8, 2, 4), (3, 2, 2), (6, 4, 3),
    ])
    def test_finish_ticks_match_simulator(self, m, pp, vpp):
        occupancy, finish = simulate_schedule(m, pp, vpp)
        assert finish == interleaved_finish_ticks(m, pp, vpp)
        # every mb visits all pp*vpp vstages in order, exactly once
        visits = {}
        for (t, r), (mb, vs) in sorted(occupancy.items()):
            visits.setdefault(mb, []).append(vs)
        for mb in range(m):
            assert visits[mb] == list(range(pp * vpp))

    @pytest.mark.parametrize("m,pp,vpp", [(4, 2, 2), (8, 4, 2), (3, 2, 2)])
    def test_traced_index_math_matches_simulator(self, m, pp, vpp):
        """The (chunk, microbatch, live) formulas the traced tick loop uses
        must reproduce the simulator's occupancy exactly."""
        occupancy, finish = simulate_schedule(m, pp, vpp)
        total_ticks = max(finish) + 1
        for t in range(total_ticks):
            for r in range(pp):
                assert occupancy.get((t, r)) == expected_occupancy(
                    t, r, m, pp, vpp), (t, r)

    def test_bubble_accounting(self):
        # M=8, pp=4: afab bubble 3/11; vpp=2 cuts it to 3/19 with step time
        # 19/(2*11) = 0.864 of afab's
        acct = interleaved_tick_schedule(8, 4, 2)
        assert acct["ticks"] == 8 * 2 + 4 - 1 == 19
        assert acct["bubble_ticks"] == 3
        assert acct["bubble_fraction"] == pytest.approx(3 / 19)
        assert acct["afab_bubble_fraction"] == pytest.approx(3 / 11)
        assert acct["relative_step_time"] == pytest.approx(19 / 22)
        # more virtual stages -> strictly smaller bubble fraction and step
        # time (M % pp == 0 keeps cohorts full)
        prev = interleaved_tick_schedule(8, 4, 2)
        for vpp in (3, 4, 6):
            cur = interleaved_tick_schedule(8, 4, vpp)
            assert cur["bubble_fraction"] < prev["bubble_fraction"]
            assert cur["relative_step_time"] < prev["relative_step_time"]
            prev = cur

    def test_randomized_schedule_space(self):
        """Property sweep: 200 random (m, pp, vpp) triples — the traced
        index math must match the simulator everywhere, not just the
        hand-picked cases (insurance against off-by-ones in corners like
        m < pp or vpp > m)."""
        rng = np.random.default_rng(7)
        for _ in range(200):
            pp = int(rng.integers(1, 6))
            vpp = int(rng.integers(2, 5))
            m = int(rng.integers(1, 13))
            occupancy, finish = simulate_schedule(m, pp, vpp)
            assert finish == interleaved_finish_ticks(m, pp, vpp), (m, pp, vpp)
            for t in range(max(finish) + 1):
                for r in range(pp):
                    assert occupancy.get((t, r)) == expected_occupancy(
                        t, r, m, pp, vpp), (m, pp, vpp, t, r)

    def test_validation(self):
        validate_interleaved_divisibility(8, 2, 2)
        with pytest.raises(ValueError, match="pp_virtual_stages"):
            validate_interleaved_divisibility(8, 2, 1)
        with pytest.raises(ValueError, match="not divisible"):
            validate_interleaved_divisibility(6, 2, 2)


class TestParamReblocking:
    def test_roundtrip_and_ownership(self):
        layers = {"w": jnp.arange(8 * 3, dtype=jnp.float32).reshape(8, 3)}
        inter = interleave_stacked_params(layers, 8, pp=2, vpp=2)
        # rank 0 shard (rows 0..3) = vstage 0 (layers 0,1) + vstage 2
        # (layers 4,5); rank 1 = vstage 1 (2,3) + vstage 3 (6,7)
        np.testing.assert_array_equal(
            np.asarray(inter["w"][:, 0]), [0, 3, 12, 15, 6, 9, 18, 21])
        back = deinterleave_stacked_params(inter, 8, pp=2, vpp=2)
        np.testing.assert_array_equal(np.asarray(back["w"]),
                                      np.asarray(layers["w"]))

    def test_uniform_stack_guard(self):
        ragged = {"a": jnp.zeros((8, 2)), "b": jnp.zeros((4, 2))}
        with pytest.raises(ValueError, match="uniformly stacked"):
            interleave_stacked_params(ragged, 8, pp=2, vpp=2)


def _golden(params, ids, targets):
    from scaletorch_tpu.models.llama import lm_head_weight
    from scaletorch_tpu.parallel.tensor_parallel import (
        fused_vocab_parallel_cross_entropy,
    )

    def loss_fn(p):
        losses = []
        for i in range(ids.shape[0]):
            hidden = forward(p, ids[i], CFG, return_hidden=True)
            losses.append(fused_vocab_parallel_cross_entropy(
                hidden, lm_head_weight(p, CFG), targets[i], axis=None
            ))
        return jnp.mean(jnp.stack(losses))

    return jax.value_and_grad(loss_fn)(params)


def _run_interleaved(mm, vpp, params, ids, targets, **kw):
    """Loss + grads through the interleaved pipeline; grads are returned
    in TRUE layer order (deinterleaved) for direct golden comparison."""
    from scaletorch_tpu.parallel.tensor_parallel import (
        llama_param_specs,
        pvary_missing,
    )

    pipe_loss = make_llama_pipeline_loss(mm, CFG, vpp=vpp, **kw)
    p_specs = llama_param_specs(
        CFG, tp_axis="tp" if mm.tp > 1 else None, pp_axis="pp"
    )
    m, _, s = ids.shape
    batch = {
        "input_ids": ids,
        "target_ids": targets,
        "position_ids": np.broadcast_to(
            np.arange(s, dtype=np.int32), (m, s)
        ).copy(),
    }
    b_specs = {
        "input_ids": P(None, "dp", None),
        "target_ids": P(None, "dp", None),
        "position_ids": P(None, None),
    }

    def mean_loss(p, b):
        axes = ("dp", "cp", "ep", "tp", "pp")
        return jax.lax.pmean(pvary_missing(pipe_loss(p, b), axes), axes)

    f = jax.jit(
        jax.value_and_grad(
            jax.shard_map(
                mean_loss, mesh=mm.mesh,
                in_specs=(p_specs, b_specs), out_specs=P(),
            )
        )
    )
    params_i = dict(params, layers=interleave_stacked_params(
        params["layers"], CFG.num_hidden_layers, mm.pp, vpp))
    loss, grads = f(params_i, batch)
    grads = dict(grads, layers=deinterleave_stacked_params(
        grads["layers"], CFG.num_hidden_layers, mm.pp, vpp))
    return loss, grads


@pytest.fixture(scope="module")
def setup():
    params = init_params(jax.random.PRNGKey(0), CFG)
    ids = jax.random.randint(jax.random.PRNGKey(1), (4, 4, 16), 0, CFG.vocab_size)
    targets = jax.random.randint(jax.random.PRNGKey(2), (4, 4, 16), 0, CFG.vocab_size)
    loss, grads = _golden(params, ids, targets)
    return params, ids, targets, loss, grads


@pytest.mark.slow
class TestInterleavedNumerics:
    @pytest.mark.parametrize("pp,vpp", [(2, 2), (4, 2), (2, 4)])
    def test_matches_single_device(self, setup, pp, vpp):
        params, ids, targets, ref_loss, ref_grads = setup
        mm = MeshManager(pp=pp, dp=8 // pp)
        loss, grads = _run_interleaved(mm, vpp, params, ids, targets)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=5e-4, atol=2e-5),
            grads, ref_grads,
        )

    def test_partial_cohort(self, setup):
        """M=3 with pp=2: the last cohort has one dead slot; its masked
        ticks must contribute nothing."""
        params, ids, targets, _, _ = setup
        ids3, targets3 = ids[:3], targets[:3]
        ref_loss, ref_grads = _golden(params, ids3, targets3)
        mm = MeshManager(pp=2, dp=4)
        loss, grads = _run_interleaved(mm, 2, params, ids3, targets3)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=5e-4, atol=2e-5),
            grads, ref_grads,
        )

    def test_with_tp(self, setup):
        params, ids, targets, ref_loss, ref_grads = setup
        mm = MeshManager(pp=2, tp=2, dp=2)
        loss, grads = _run_interleaved(mm, 2, params, ids, targets)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=5e-4, atol=2e-5),
            grads, ref_grads,
        )


@pytest.mark.slow
class TestInterleavedTrainStep:
    def test_step_matches_afab(self):
        """Same data, same init: the interleaved engine's first optimizer
        step must land on the same loss and (deinterleaved) params as
        afab — the schedules reorder compute, not math."""
        from scaletorch_tpu.config import ScaleTorchTPUArguments
        from scaletorch_tpu.parallel.spmd import make_spmd_train_step, shard_params
        from scaletorch_tpu.trainer.optimizer import create_optimizer

        mm = MeshManager(pp=2, dp=4)
        params = init_params(jax.random.PRNGKey(0), CFG)
        rng = np.random.default_rng(0)
        accum, bsz, seq = 4, 4, 16
        ids = rng.integers(0, CFG.vocab_size, (accum, bsz, seq + 1))
        batch = {
            "input_ids": ids[:, :, :-1].astype(np.int32),
            "target_ids": ids[:, :, 1:].astype(np.int32),
            "position_ids": np.broadcast_to(
                np.arange(seq, dtype=np.int32), (accum, seq)
            ).copy(),
        }
        tcfg = ScaleTorchTPUArguments(
            learning_rate=1e-3, total_train_steps=10, warmup_steps=0
        )
        results = {}
        for schedule in ("afab", "interleaved"):
            p_host = params
            if schedule == "interleaved":
                p_host = dict(params, layers=interleave_stacked_params(
                    params["layers"], CFG.num_hidden_layers, mm.pp, 2))
            tx, _ = create_optimizer(tcfg, include_clip=False)
            step_fn, p_specs, o_specs = make_spmd_train_step(
                mm, forward, CFG, tx, p_host,
                max_grad_norm=1.0, pp_schedule=schedule, pp_vpp=2,
                donate=False,
            )
            p2, _, m = step_fn(
                shard_params(mm, p_host, p_specs),
                shard_params(mm, tx.init(p_host), o_specs),
                batch,
            )
            p2 = jax.device_get(p2)
            if schedule == "interleaved":
                p2 = dict(p2, layers=deinterleave_stacked_params(
                    p2["layers"], CFG.num_hidden_layers, mm.pp, 2))
            results[schedule] = (float(m["loss"]), p2)
        assert results["interleaved"][0] == pytest.approx(
            results["afab"][0], rel=1e-5)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, atol=1e-5),
            results["interleaved"][1], results["afab"][1],
        )


@pytest.mark.slow
class TestInterleavedMoE:
    def test_moe_matches_single_device(self):
        """PP x EP interleaved: loss (CE + aux) must match the flat
        single-device step; routing stats stay finite."""
        from scaletorch_tpu.config import ScaleTorchTPUArguments
        from scaletorch_tpu.models.qwen3_moe import (
            Qwen3MoEConfig,
            forward as moe_forward,
            init_params as moe_init,
            qwen3_moe_param_specs,
        )
        from scaletorch_tpu.parallel.spmd import make_spmd_train_step, shard_params
        from scaletorch_tpu.trainer.optimizer import create_optimizer
        from scaletorch_tpu.trainer.train_step import make_train_step

        cfg = Qwen3MoEConfig(
            vocab_size=128, hidden_size=32, intermediate_size=64,
            moe_intermediate_size=48, num_hidden_layers=4,
            num_attention_heads=4, num_key_value_heads=4, head_dim=8,
            num_experts=4, num_experts_per_tok=2, capacity_factor=8.0,
            dtype=jnp.float32, qk_norm=True, tie_word_embeddings=False,
        )
        params = moe_init(jax.random.PRNGKey(0), cfg)
        rng = np.random.default_rng(0)
        accum, bsz, seq = 2, 4, 16
        ids = rng.integers(0, cfg.vocab_size, (accum, bsz, seq + 1))
        batch = {
            "input_ids": ids[:, :, :-1].astype(np.int32),
            "target_ids": ids[:, :, 1:].astype(np.int32),
            "position_ids": np.broadcast_to(
                np.arange(seq, dtype=np.int32), (accum, seq)
            ).copy(),
        }
        tcfg = ScaleTorchTPUArguments(
            learning_rate=1e-3, total_train_steps=10, warmup_steps=0
        )
        tx_ref, _ = create_optimizer(tcfg, include_clip=False)
        ref_step = make_train_step(moe_forward, cfg, tx_ref, donate=False)
        _, _, m_ref = ref_step(params, tx_ref.init(params), batch)

        mm = MeshManager(pp=2, dp=4)
        p_host = dict(params, layers=interleave_stacked_params(
            params["layers"], 4, mm.pp, 2))
        tx, _ = create_optimizer(tcfg, include_clip=False)
        specs = qwen3_moe_param_specs(cfg, tp_axis="tp", pp_axis="pp")
        step_fn, p_specs, o_specs = make_spmd_train_step(
            mm, moe_forward, cfg, tx, p_host,
            max_grad_norm=0.0, donate=False, param_specs=specs,
            model_family="qwen3_moe", pp_schedule="interleaved", pp_vpp=2,
        )
        _, _, m = step_fn(
            shard_params(mm, p_host, p_specs),
            shard_params(mm, tx.init(p_host), o_specs),
            batch,
        )
        assert float(m["loss"]) == pytest.approx(float(m_ref["loss"]), rel=5e-6)
        assert np.isfinite(float(m["moe_load_cv"]))
        assert 0.0 <= float(m["moe_dropped_fraction"]) <= 1.0


@pytest.mark.slow
class TestInterleavedComposition:
    """The engine must compose with the other mesh axes exactly like
    afab does: CP (ring attention inside chunk compute, sequence-sharded
    carries) and EP (expert all-to-all inside lax.switch branches —
    sound because ep groups never span pp, so a group always takes the
    same branch together)."""

    def test_with_cp_zigzag_ring(self):
        from scaletorch_tpu.config import ScaleTorchTPUArguments
        from scaletorch_tpu.parallel.spmd import make_spmd_train_step, shard_params
        from scaletorch_tpu.parallel.zigzag import zigzag_batch
        from scaletorch_tpu.trainer.optimizer import create_optimizer
        from scaletorch_tpu.trainer.train_step import make_train_step

        params = init_params(jax.random.PRNGKey(0), CFG)
        rng = np.random.default_rng(0)
        accum, bsz, seq = 2, 2, 32  # seq % (2*cp) == 0
        ids = rng.integers(0, CFG.vocab_size, (accum, bsz, seq + 1))
        batch = {
            "input_ids": ids[:, :, :-1].astype(np.int32),
            "target_ids": ids[:, :, 1:].astype(np.int32),
            "position_ids": np.broadcast_to(
                np.arange(seq, dtype=np.int32), (accum, seq)
            ).copy(),
        }
        tcfg = ScaleTorchTPUArguments(
            learning_rate=1e-3, total_train_steps=10, warmup_steps=0
        )
        tx_ref, _ = create_optimizer(tcfg, include_clip=False)
        ref_step = make_train_step(forward, CFG, tx_ref, donate=False)
        _, _, m_ref = ref_step(params, tx_ref.init(params), batch)

        mm = MeshManager(pp=2, cp=2, dp=2)
        p_host = dict(params, layers=interleave_stacked_params(
            params["layers"], CFG.num_hidden_layers, mm.pp, 2))
        tx, _ = create_optimizer(tcfg, include_clip=False)
        step_fn, p_specs, o_specs = make_spmd_train_step(
            mm, forward, CFG, tx, p_host,
            attention_backend="ring", cp_layout="zigzag",
            max_grad_norm=0.0, donate=False,
            pp_schedule="interleaved", pp_vpp=2,
        )
        _, _, m = step_fn(
            shard_params(mm, p_host, p_specs),
            shard_params(mm, tx.init(p_host), o_specs),
            zigzag_batch(batch, mm.cp),
        )
        assert float(m["loss"]) == pytest.approx(float(m_ref["loss"]), rel=2e-5)

    def test_with_ep_all_to_all(self):
        from scaletorch_tpu.config import ScaleTorchTPUArguments
        from scaletorch_tpu.models.qwen3_moe import (
            Qwen3MoEConfig,
            forward as moe_forward,
            init_params as moe_init,
            qwen3_moe_param_specs,
        )
        from scaletorch_tpu.parallel.spmd import make_spmd_train_step, shard_params
        from scaletorch_tpu.trainer.optimizer import create_optimizer
        from scaletorch_tpu.trainer.train_step import make_train_step

        cfg = Qwen3MoEConfig(
            vocab_size=128, hidden_size=32, intermediate_size=64,
            moe_intermediate_size=48, num_hidden_layers=4,
            num_attention_heads=4, num_key_value_heads=4, head_dim=8,
            num_experts=4, num_experts_per_tok=2, capacity_factor=8.0,
            dtype=jnp.float32, qk_norm=True, tie_word_embeddings=False,
        )
        params = moe_init(jax.random.PRNGKey(0), cfg)
        rng = np.random.default_rng(0)
        accum, bsz, seq = 2, 4, 16
        ids = rng.integers(0, cfg.vocab_size, (accum, bsz, seq + 1))
        batch = {
            "input_ids": ids[:, :, :-1].astype(np.int32),
            "target_ids": ids[:, :, 1:].astype(np.int32),
            "position_ids": np.broadcast_to(
                np.arange(seq, dtype=np.int32), (accum, seq)
            ).copy(),
        }
        tcfg = ScaleTorchTPUArguments(
            learning_rate=1e-3, total_train_steps=10, warmup_steps=0
        )
        tx_ref, _ = create_optimizer(tcfg, include_clip=False)
        ref_step = make_train_step(moe_forward, cfg, tx_ref, donate=False)
        _, _, m_ref = ref_step(params, tx_ref.init(params), batch)

        mm = MeshManager(pp=2, ep=2, dp=2)
        p_host = dict(params, layers=interleave_stacked_params(
            params["layers"], 4, mm.pp, 2))
        tx, _ = create_optimizer(tcfg, include_clip=False)
        specs = qwen3_moe_param_specs(
            cfg, tp_axis="tp", ep_axis="ep", pp_axis="pp")
        step_fn, p_specs, o_specs = make_spmd_train_step(
            mm, moe_forward, cfg, tx, p_host,
            max_grad_norm=0.0, donate=False, param_specs=specs,
            model_kwargs={"ep_axis": "ep"},
            model_family="qwen3_moe", pp_schedule="interleaved", pp_vpp=2,
        )
        _, _, m = step_fn(
            shard_params(mm, p_host, p_specs),
            shard_params(mm, tx.init(p_host), o_specs),
            batch,
        )
        assert float(m["loss"]) == pytest.approx(float(m_ref["loss"]), rel=5e-6)
        assert np.isfinite(float(m["moe_load_cv"]))


class TestStepGuards:
    """make_spmd_train_step must refuse the silently-wrong combinations
    (code-review r5): a mis-sized layer axis (basic slicing would CLIP,
    not error) and an opaque custom loss with the engine flag."""

    def _mk(self, **kw):
        from scaletorch_tpu.config import ScaleTorchTPUArguments
        from scaletorch_tpu.parallel.spmd import make_spmd_train_step
        from scaletorch_tpu.trainer.optimizer import create_optimizer

        mm = MeshManager(pp=2, dp=4)
        params = kw.pop("params", init_params(jax.random.PRNGKey(0), CFG))
        tcfg = ScaleTorchTPUArguments(
            learning_rate=1e-3, total_train_steps=10, warmup_steps=0
        )
        tx, _ = create_optimizer(tcfg, include_clip=False)
        return make_spmd_train_step(
            mm, forward, CFG, tx, params,
            pp_schedule="interleaved", pp_vpp=2, donate=False, **kw,
        )

    def test_mis_sized_layer_axis_raises(self):
        params = init_params(jax.random.PRNGKey(0), CFG)
        bad = dict(params, layers=jax.tree.map(
            lambda w: jnp.concatenate([w, w[:2]], 0), params["layers"]))
        with pytest.raises(ValueError, match="stacked layer axis"):
            self._mk(params=bad)

    def test_custom_loss_with_interleaved_raises(self):
        from scaletorch_tpu.parallel.tensor_parallel import llama_param_specs

        mm = MeshManager(pp=2, dp=4)
        with pytest.raises(ValueError, match="custom_pipeline_loss"):
            self._mk(
                param_specs=llama_param_specs(CFG, tp_axis="tp", pp_axis="pp"),
                custom_pipeline_loss=make_llama_pipeline_loss(mm, CFG),
            )


class TestConfigKnobs:
    def test_interleaved_requires_vpp(self):
        from scaletorch_tpu.config import ParallelArguments

        with pytest.raises(ValueError, match="pp_virtual_stages >= 2"):
            ParallelArguments(pp_engine="interleaved")
        pa = ParallelArguments(pp_engine="interleaved", pp_virtual_stages=2)
        assert pa.pp_virtual_stages == 2
        # 0 = auto sentinel, resolved by the Trainer
        pa = ParallelArguments(pp_engine="interleaved", pp_virtual_stages=0)
        assert pa.pp_virtual_stages == 0

    def test_suggest_virtual_stages(self):
        from scaletorch_tpu.parallel.pipeline_parallel import (
            suggest_virtual_stages,
        )

        assert suggest_virtual_stages(8, 2) == 4       # per-rank 4
        assert suggest_virtual_stages(28, 2) == 2      # per-rank 14: 4,3 no; 2 yes
        assert suggest_virtual_stages(36, 2) == 3      # per-rank 18: 4 no; 3 yes
        assert suggest_virtual_stages(48, 2) == 4
        assert suggest_virtual_stages(10, 2) == 1      # per-rank 5: no divisor
        assert suggest_virtual_stages(8, 3) == 1       # pp doesn't divide L
        assert suggest_virtual_stages(8, 1) == 1       # no pipeline

    def test_vpp_requires_interleaved(self):
        from scaletorch_tpu.config import ParallelArguments

        with pytest.raises(ValueError, match="requires"):
            ParallelArguments(pp_engine="afab", pp_virtual_stages=2)
