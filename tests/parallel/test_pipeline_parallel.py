"""Pipeline parallelism: partition math parity + golden numerics.

The reference tests layer distribution and schedule bookkeeping against a
mocked pgm (tests/parallel/test_pipeline_parallel.py); here the partition
math is tested pure and the full SPMD collective-permute pipeline runs on
the 8-virtual-device mesh, checked against the single-device forward/
backward — loss AND gradients must match to fp32 tolerance.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from scaletorch_tpu.models.llama import LlamaConfig, forward, init_params
from scaletorch_tpu.parallel.mesh import MeshManager
from scaletorch_tpu.parallel.pipeline_parallel import (
    make_llama_pipeline_loss,
    stage_layer_partition,
    validate_pp_divisibility,
)

CFG = LlamaConfig(
    vocab_size=128, hidden_size=32, intermediate_size=64, num_hidden_layers=4,
    num_attention_heads=4, num_key_value_heads=4, dtype=jnp.float32,
)


class TestStagePartition:
    def test_even_split(self):
        assert stage_layer_partition(8, 4) == [[0, 1], [2, 3], [4, 5], [6, 7]]

    def test_remainder_to_early_stages(self):
        # parity: reference distribute_layers, pipeline_parallel.py:83-133
        assert stage_layer_partition(10, 4) == [
            [0, 1, 2], [3, 4, 5], [6, 7], [8, 9]
        ]

    def test_custom_distribution(self):
        assert stage_layer_partition(6, 3, [1, 2, 3]) == [[0], [1, 2], [3, 4, 5]]

    def test_custom_distribution_errors(self):
        with pytest.raises(ValueError, match="sums to"):
            stage_layer_partition(6, 3, [1, 2, 2])
        with pytest.raises(ValueError, match="entries"):
            stage_layer_partition(6, 3, [3, 3])
        with pytest.raises(ValueError, match=">= 1"):
            stage_layer_partition(6, 3, [0, 3, 3])

    def test_more_stages_than_layers(self):
        with pytest.raises(ValueError, match="every stage needs"):
            stage_layer_partition(2, 4)

    def test_validate_divisibility(self):
        validate_pp_divisibility(CFG, 2)
        with pytest.raises(ValueError, match="not divisible"):
            validate_pp_divisibility(CFG, 3)


def _golden(params, ids, targets):
    """Single-device loss + grads: mean over microbatches of per-mb CE
    (same fused-CE token math as the pipeline path, so tolerances stay at
    fp32 roundoff rather than accumulation-order noise)."""
    from scaletorch_tpu.models.llama import lm_head_weight
    from scaletorch_tpu.parallel.tensor_parallel import (
        fused_vocab_parallel_cross_entropy,
    )

    def loss_fn(p):
        losses = []
        for i in range(ids.shape[0]):
            hidden = forward(p, ids[i], CFG, return_hidden=True)
            losses.append(fused_vocab_parallel_cross_entropy(
                hidden, lm_head_weight(p, CFG), targets[i], axis=None
            ))
        return jnp.mean(jnp.stack(losses))

    return jax.value_and_grad(loss_fn)(params)


def _pipeline(mm, params, ids, targets, **kw):
    from scaletorch_tpu.parallel.tensor_parallel import llama_param_specs

    pipe_loss = make_llama_pipeline_loss(mm, CFG, **kw)
    p_specs = llama_param_specs(
        CFG, tp_axis="tp" if mm.tp > 1 else None, pp_axis="pp"
    )
    b_specs = {
        "input_ids": P(None, "dp", "cp" if mm.cp > 1 else None),
        "target_ids": P(None, "dp", "cp" if mm.cp > 1 else None),
        "position_ids": P(None, "cp" if mm.cp > 1 else None),
    }
    m, _, s = ids.shape
    batch = {
        "input_ids": ids,
        "target_ids": targets,
        "position_ids": np.broadcast_to(
            np.arange(s, dtype=np.int32), (m, s)
        ).copy(),
    }
    from scaletorch_tpu.parallel.tensor_parallel import pvary_missing

    def mean_loss(p, b):
        axes = ("dp", "cp", "ep", "tp", "pp")
        return jax.lax.pmean(pvary_missing(pipe_loss(p, b), axes), axes)

    f = jax.jit(
        jax.value_and_grad(
            jax.shard_map(
                mean_loss, mesh=mm.mesh,
                in_specs=(p_specs, b_specs), out_specs=P(),
            )
        )
    )
    return f(params, batch)


@pytest.fixture(scope="module")
def setup():
    params = init_params(jax.random.PRNGKey(0), CFG)
    ids = jax.random.randint(jax.random.PRNGKey(1), (4, 4, 16), 0, CFG.vocab_size)
    targets = jax.random.randint(jax.random.PRNGKey(2), (4, 4, 16), 0, CFG.vocab_size)
    loss, grads = _golden(params, ids, targets)
    return params, ids, targets, loss, grads


@pytest.mark.slow
class TestPipelineNumerics:
    @pytest.mark.parametrize("pp", [2, 4])
    def test_pp_matches_single_device(self, setup, pp):
        params, ids, targets, ref_loss, ref_grads = setup
        mm = MeshManager(pp=pp, dp=8 // pp)
        loss, grads = _pipeline(mm, params, ids, targets)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)
        # pipeline grads are per-parameter partials; only loss grads w.r.t.
        # full params compare (specs gather shards back automatically
        # outside shard_map)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=5e-4, atol=2e-5),
            grads, ref_grads,
        )

    def test_pp_with_tp(self, setup):
        params, ids, targets, ref_loss, ref_grads = setup
        mm = MeshManager(pp=2, tp=2, dp=2)
        loss, grads = _pipeline(mm, params, ids, targets)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=5e-4, atol=2e-5),
            grads, ref_grads,
        )

    def test_pp_with_tp_sp(self, setup):
        params, ids, targets, ref_loss, ref_grads = setup
        mm = MeshManager(pp=2, tp=2, dp=2)
        loss, grads = _pipeline(
            mm, params, ids, targets, sequence_parallel=True
        )
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=5e-4, atol=2e-5),
            grads, ref_grads,
        )

    def test_pp_gradient_checkpointing(self, setup):
        params, ids, targets, ref_loss, _ = setup
        mm = MeshManager(pp=2, dp=4)
        loss, _ = _pipeline(mm, params, ids, targets, gradient_checkpointing=True)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)


@pytest.mark.slow
class TestPipelineTrainStep:
    @pytest.mark.parametrize("schedule", ["afab", "1f1b"])
    def test_spmd_step_with_pp(self, schedule):
        from scaletorch_tpu.config import ScaleTorchTPUArguments
        from scaletorch_tpu.parallel.spmd import make_spmd_train_step, shard_params
        from scaletorch_tpu.trainer.optimizer import create_optimizer

        mm = MeshManager(pp=2, tp=2, dp=2)
        params = init_params(jax.random.PRNGKey(0), CFG)
        tcfg = ScaleTorchTPUArguments(
            learning_rate=1e-3, total_train_steps=10, warmup_steps=0
        )
        tx, _ = create_optimizer(tcfg, include_clip=False)
        step_fn, p_specs, o_specs = make_spmd_train_step(
            mm, forward, CFG, tx, params,
            max_grad_norm=1.0, pp_schedule=schedule, donate=False,
        )
        params_s = shard_params(mm, params, p_specs)
        opt_state = shard_params(mm, tx.init(params), o_specs)

        rng = np.random.default_rng(0)
        accum, bsz, seq = 2, 2, 16
        ids = rng.integers(0, CFG.vocab_size, (accum, bsz, seq + 1))
        batch = {
            "input_ids": ids[:, :, :-1].astype(np.int32),
            "target_ids": ids[:, :, 1:].astype(np.int32),
            "position_ids": np.broadcast_to(
                np.arange(seq, dtype=np.int32), (accum, seq)
            ).copy(),
        }
        p2, o2, metrics = step_fn(params_s, opt_state, batch)
        assert np.isfinite(float(metrics["loss"]))
        assert np.isfinite(float(metrics["grad_norm"]))
        # params actually changed (compare against the host copy —
        # params_s was donated into the step)
        delta = jax.tree.map(
            lambda a, b: float(jnp.max(jnp.abs(jnp.asarray(a) - b))), p2, params
        )
        assert max(jax.tree.leaves(delta)) > 0

    def test_1f1b_uneven_accum_matches_afab(self):
        """accum % pp != 0: the 1f1b chunked schedule covers the tail with
        a shorter remainder pipeline pass (the reference 1F1B handles any
        M >= 1); the step must compute the identical weighted-mean
        gradient as afab, which differentiates all 6 microbatches at once."""
        from scaletorch_tpu.config import ScaleTorchTPUArguments
        from scaletorch_tpu.parallel.spmd import make_spmd_train_step, shard_params
        from scaletorch_tpu.trainer.optimizer import create_optimizer

        mm = MeshManager(pp=4, dp=2)
        params = init_params(jax.random.PRNGKey(0), CFG)
        rng = np.random.default_rng(1)
        accum, bsz, seq = 6, 2, 16
        ids = rng.integers(0, CFG.vocab_size, (accum, bsz, seq + 1))
        batch = {
            "input_ids": ids[:, :, :-1].astype(np.int32),
            "target_ids": ids[:, :, 1:].astype(np.int32),
            "position_ids": np.broadcast_to(
                np.arange(seq, dtype=np.int32), (accum, seq)
            ).copy(),
        }
        tcfg = ScaleTorchTPUArguments(
            learning_rate=1e-3, total_train_steps=10, warmup_steps=0
        )
        results = {}
        for schedule in ("afab", "1f1b"):
            tx, _ = create_optimizer(tcfg, include_clip=False)
            step_fn, p_specs, o_specs = make_spmd_train_step(
                mm, forward, CFG, tx, params,
                max_grad_norm=1.0, pp_schedule=schedule, donate=False,
            )
            p2, _, m = step_fn(
                shard_params(mm, params, p_specs),
                shard_params(mm, tx.init(params), o_specs),
                batch,
            )
            results[schedule] = (float(m["loss"]), jax.device_get(p2))
        assert results["1f1b"][0] == pytest.approx(results["afab"][0], rel=1e-5)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, atol=1e-5),
            results["1f1b"][1], results["afab"][1],
        )


@pytest.mark.slow
class TestUnevenPipeline:
    """Uneven layer counts: pad the stacked axis, mask identity slots
    (reference PipelineParallel ragged stage counts,
    pipeline_parallel.py:83-133)."""

    def test_pad_unpad_roundtrip(self):
        from scaletorch_tpu.parallel.pipeline_parallel import (
            pad_stacked_params,
            padded_stage_counts,
            unpad_stacked_params,
        )

        counts, slots = padded_stage_counts(6, 4)
        assert counts == [2, 2, 1, 1] and slots == 2
        layers = {"w": jnp.arange(6 * 3, dtype=jnp.float32).reshape(6, 3)}
        padded = pad_stacked_params(layers, 6, 4)
        assert padded["w"].shape == (8, 3)
        # stage blocks: [l0,l1 | l2,l3 | l4,pad | l5,pad]
        np.testing.assert_allclose(padded["w"][4], layers["w"][4])
        np.testing.assert_allclose(padded["w"][5], 0.0)
        np.testing.assert_allclose(padded["w"][6], layers["w"][5])
        np.testing.assert_allclose(padded["w"][7], 0.0)
        restored = unpad_stacked_params(padded, 6, 4)
        np.testing.assert_allclose(restored["w"], layers["w"])
        # even split is a no-op (identity, no copy)
        assert pad_stacked_params(layers, 6, 2) is layers

    @pytest.mark.parametrize("pp,dp,n_layers", [(2, 4, 3), (4, 2, 6)])
    def test_uneven_pp_step_matches_single_device(self, pp, dp, n_layers):
        from scaletorch_tpu.config import ScaleTorchTPUArguments
        from scaletorch_tpu.parallel.pipeline_parallel import pad_stacked_params
        from scaletorch_tpu.parallel.spmd import make_spmd_train_step, shard_params
        from scaletorch_tpu.trainer.optimizer import create_optimizer
        from scaletorch_tpu.trainer.train_step import make_train_step

        cfg = LlamaConfig(
            vocab_size=CFG.vocab_size, hidden_size=CFG.hidden_size,
            intermediate_size=CFG.intermediate_size,
            num_hidden_layers=n_layers,
            num_attention_heads=CFG.num_attention_heads,
            num_key_value_heads=CFG.num_key_value_heads,
            dtype=jnp.float32,
        )
        params = init_params(jax.random.PRNGKey(0), cfg)
        rng = np.random.default_rng(0)
        accum, bsz, seq = 2, dp, 16  # batch rows shard over dp
        ids = rng.integers(0, cfg.vocab_size, (accum, bsz, seq + 1))
        batch = {
            "input_ids": ids[:, :, :-1].astype(np.int32),
            "target_ids": ids[:, :, 1:].astype(np.int32),
            "position_ids": np.broadcast_to(
                np.arange(seq, dtype=np.int32), (accum, seq)
            ).copy(),
        }
        tcfg = ScaleTorchTPUArguments(
            learning_rate=1e-3, total_train_steps=10, warmup_steps=0
        )
        tx_ref, _ = create_optimizer(tcfg, include_clip=False)
        ref_step = make_train_step(forward, cfg, tx_ref, donate=False)
        _, _, m_ref = ref_step(params, tx_ref.init(params), batch)

        mm = MeshManager(pp=pp, dp=dp)
        padded = dict(params, layers=pad_stacked_params(
            params["layers"], n_layers, pp))
        tx, _ = create_optimizer(tcfg, include_clip=False)
        step_fn, p_specs, o_specs = make_spmd_train_step(
            mm, forward, cfg, tx, padded, max_grad_norm=0.0, donate=False,
        )
        _, _, m = step_fn(
            shard_params(mm, padded, p_specs),
            shard_params(mm, tx.init(padded), o_specs),
            batch,
        )
        assert float(m["loss"]) == pytest.approx(float(m_ref["loss"]), rel=2e-5)

    def test_trainer_pads_uneven_pp_automatically(self):
        from scaletorch_tpu.config import ScaleTorchTPUArguments
        from scaletorch_tpu.trainer.trainer import Trainer

        losses = {}
        for name, pp in {"pp1": 1, "pp2": 2}.items():
            cfg = ScaleTorchTPUArguments(
                model_type="llama", hidden_size=32, intermediate_size=64,
                num_hidden_layers=3, num_attention_heads=4,
                num_key_value_heads=2, vocab_size=64, sequence_length=16,
                max_position_embeddings=32,
                pipeline_parallel_size=pp,
                data_parallel_size=8 // pp,
                # keep the GLOBAL batch (micro_bs * dp) constant across
                # meshes so the two runs see identical data
                micro_batch_size=2 * pp, gradient_accumulation_steps=2,
                synthetic_data=True, total_train_steps=2, dtype="float32",
                donate_params=False, log_frequency=100,
            )
            t = Trainer(cfg)
            try:
                it = iter(t.loader)
                for _ in range(2):
                    b = t._device_batch(next(it))
                    t.params, t.opt_state, m = t.step_fn(
                        t.params, t.opt_state, b)
                losses[name] = float(m["loss"])
            finally:
                t.close()
        assert losses["pp2"] == pytest.approx(losses["pp1"], rel=2e-4)


@pytest.mark.slow
class TestCustomPipelineProtocol:
    def test_custom_family_runs_pp_via_pipeline_spmd_loss(self):
        """The documented custom-model PP hook: a caller-supplied
        pipeline loss (built on pipeline_spmd_loss) lifts the
        custom-params guard and trains to the built-in path's loss."""
        from scaletorch_tpu.config import ScaleTorchTPUArguments
        from scaletorch_tpu.parallel.pipeline_parallel import (
            make_llama_pipeline_loss,
        )
        from scaletorch_tpu.parallel.spmd import make_spmd_train_step, shard_params
        from scaletorch_tpu.parallel.tensor_parallel import llama_param_specs
        from scaletorch_tpu.trainer.optimizer import create_optimizer

        mm = MeshManager(pp=2, dp=4)
        params = init_params(jax.random.PRNGKey(0), CFG)
        specs = llama_param_specs(CFG, tp_axis="tp", pp_axis="pp")
        tcfg = ScaleTorchTPUArguments(
            learning_rate=1e-3, total_train_steps=10, warmup_steps=0
        )
        rng = np.random.default_rng(0)
        accum, bsz, seq = 2, 4, 16  # batch rows shard over dp=4
        ids = rng.integers(0, CFG.vocab_size, (accum, bsz, seq + 1))
        batch = {
            "input_ids": ids[:, :, :-1].astype(np.int32),
            "target_ids": ids[:, :, 1:].astype(np.int32),
            "position_ids": np.broadcast_to(
                np.arange(seq, dtype=np.int32), (accum, seq)
            ).copy(),
        }

        results = {}
        for mode in ("builtin", "custom"):
            tx, _ = create_optimizer(tcfg, include_clip=False)
            kwargs = {}
            if mode == "custom":
                # treat llama as a "custom family": pass explicit specs
                # (which alone would raise) plus the protocol hook
                kwargs = dict(
                    param_specs=specs,
                    custom_pipeline_loss=make_llama_pipeline_loss(mm, CFG),
                )
            step_fn, p_specs, o_specs = make_spmd_train_step(
                mm, forward, CFG, tx, params,
                max_grad_norm=1.0, donate=False, **kwargs,
            )
            _, _, m = step_fn(
                shard_params(mm, params, p_specs),
                shard_params(mm, tx.init(params), o_specs),
                batch,
            )
            results[mode] = float(m["loss"])
        assert results["custom"] == pytest.approx(results["builtin"], rel=1e-6)

    def test_custom_specs_without_hook_still_guarded(self):
        from scaletorch_tpu.config import ScaleTorchTPUArguments
        from scaletorch_tpu.parallel.spmd import make_spmd_train_step
        from scaletorch_tpu.parallel.tensor_parallel import llama_param_specs
        from scaletorch_tpu.trainer.optimizer import create_optimizer

        mm = MeshManager(pp=2, dp=4)
        params = init_params(jax.random.PRNGKey(0), CFG)
        tcfg = ScaleTorchTPUArguments(
            learning_rate=1e-3, total_train_steps=10, warmup_steps=0
        )
        tx, _ = create_optimizer(tcfg, include_clip=False)
        with pytest.raises(NotImplementedError, match="custom_pipeline_loss"):
            make_spmd_train_step(
                mm, forward, CFG, tx, params,
                param_specs=llama_param_specs(CFG, tp_axis="tp", pp_axis="pp"),
            )


@pytest.mark.slow
class TestUnevenMoEPipeline:
    def test_uneven_moe_pp_step_matches_single_device(self):
        """PP x EP with a ragged layer split (L=3, pp=2): the MoE stack's
        masked padding slots must contribute neither loss nor aux."""
        from scaletorch_tpu.config import ScaleTorchTPUArguments
        from scaletorch_tpu.models.qwen3_moe import (
            Qwen3MoEConfig,
            forward as moe_forward,
            init_params as moe_init,
            qwen3_moe_param_specs,
        )
        from scaletorch_tpu.parallel.pipeline_parallel import pad_stacked_params
        from scaletorch_tpu.parallel.spmd import make_spmd_train_step, shard_params
        from scaletorch_tpu.trainer.optimizer import create_optimizer
        from scaletorch_tpu.trainer.train_step import make_train_step

        cfg = Qwen3MoEConfig(
            vocab_size=128, hidden_size=32, intermediate_size=64,
            moe_intermediate_size=48, num_hidden_layers=3,
            num_attention_heads=4, num_key_value_heads=4, head_dim=8,
            num_experts=4, num_experts_per_tok=2, capacity_factor=8.0,
            dtype=jnp.float32, qk_norm=True, tie_word_embeddings=False,
        )
        params = moe_init(jax.random.PRNGKey(0), cfg)
        rng = np.random.default_rng(0)
        accum, bsz, seq = 2, 4, 16
        ids = rng.integers(0, cfg.vocab_size, (accum, bsz, seq + 1))
        batch = {
            "input_ids": ids[:, :, :-1].astype(np.int32),
            "target_ids": ids[:, :, 1:].astype(np.int32),
            "position_ids": np.broadcast_to(
                np.arange(seq, dtype=np.int32), (accum, seq)
            ).copy(),
        }
        tcfg = ScaleTorchTPUArguments(
            learning_rate=1e-3, total_train_steps=10, warmup_steps=0
        )
        tx_ref, _ = create_optimizer(tcfg, include_clip=False)
        ref_step = make_train_step(moe_forward, cfg, tx_ref, donate=False)
        _, _, m_ref = ref_step(params, tx_ref.init(params), batch)

        mm = MeshManager(pp=2, dp=4)
        padded = dict(params, layers=pad_stacked_params(params["layers"], 3, 2))
        tx, _ = create_optimizer(tcfg, include_clip=False)
        specs = qwen3_moe_param_specs(cfg, tp_axis="tp", pp_axis="pp")
        step_fn, p_specs, o_specs = make_spmd_train_step(
            mm, moe_forward, cfg, tx, padded,
            max_grad_norm=0.0, donate=False, param_specs=specs,
            model_family="qwen3_moe",
        )
        _, _, m = step_fn(
            shard_params(mm, padded, p_specs),
            shard_params(mm, tx.init(padded), o_specs),
            batch,
        )
        # exact: CE + aux both match (the flat step's missing-aux bug was
        # the historical offset here — trainer/train_step.make_loss_fn)
        assert float(m["loss"]) == pytest.approx(float(m_ref["loss"]), rel=5e-6)
