"""Pipeline parallelism: partition math parity + golden numerics.

The reference tests layer distribution and schedule bookkeeping against a
mocked pgm (tests/parallel/test_pipeline_parallel.py); here the partition
math is tested pure and the full SPMD collective-permute pipeline runs on
the 8-virtual-device mesh, checked against the single-device forward/
backward — loss AND gradients must match to fp32 tolerance.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from scaletorch_tpu.models.layers import cross_entropy_loss
from scaletorch_tpu.models.llama import LlamaConfig, forward, init_params
from scaletorch_tpu.parallel.mesh import MeshManager
from scaletorch_tpu.parallel.pipeline_parallel import (
    make_llama_pipeline_loss,
    stage_layer_partition,
    validate_pp_divisibility,
)

CFG = LlamaConfig(
    vocab_size=128, hidden_size=32, intermediate_size=64, num_hidden_layers=4,
    num_attention_heads=4, num_key_value_heads=4, dtype=jnp.float32,
)


class TestStagePartition:
    def test_even_split(self):
        assert stage_layer_partition(8, 4) == [[0, 1], [2, 3], [4, 5], [6, 7]]

    def test_remainder_to_early_stages(self):
        # parity: reference distribute_layers, pipeline_parallel.py:83-133
        assert stage_layer_partition(10, 4) == [
            [0, 1, 2], [3, 4, 5], [6, 7], [8, 9]
        ]

    def test_custom_distribution(self):
        assert stage_layer_partition(6, 3, [1, 2, 3]) == [[0], [1, 2], [3, 4, 5]]

    def test_custom_distribution_errors(self):
        with pytest.raises(ValueError, match="sums to"):
            stage_layer_partition(6, 3, [1, 2, 2])
        with pytest.raises(ValueError, match="entries"):
            stage_layer_partition(6, 3, [3, 3])
        with pytest.raises(ValueError, match=">= 1"):
            stage_layer_partition(6, 3, [0, 3, 3])

    def test_more_stages_than_layers(self):
        with pytest.raises(ValueError, match="every stage needs"):
            stage_layer_partition(2, 4)

    def test_validate_divisibility(self):
        validate_pp_divisibility(CFG, 2)
        with pytest.raises(ValueError, match="not divisible"):
            validate_pp_divisibility(CFG, 3)


def _golden(params, ids, targets):
    """Single-device loss + grads: mean over microbatches of per-mb CE
    (same fused-CE token math as the pipeline path, so tolerances stay at
    fp32 roundoff rather than accumulation-order noise)."""
    from scaletorch_tpu.models.llama import lm_head_weight
    from scaletorch_tpu.parallel.tensor_parallel import (
        fused_vocab_parallel_cross_entropy,
    )

    def loss_fn(p):
        losses = []
        for i in range(ids.shape[0]):
            hidden = forward(p, ids[i], CFG, return_hidden=True)
            losses.append(fused_vocab_parallel_cross_entropy(
                hidden, lm_head_weight(p, CFG), targets[i], axis=None
            ))
        return jnp.mean(jnp.stack(losses))

    return jax.value_and_grad(loss_fn)(params)


def _pipeline(mm, params, ids, targets, **kw):
    from scaletorch_tpu.parallel.tensor_parallel import llama_param_specs

    pipe_loss = make_llama_pipeline_loss(mm, CFG, **kw)
    p_specs = llama_param_specs(
        CFG, tp_axis="tp" if mm.tp > 1 else None, pp_axis="pp"
    )
    b_specs = {
        "input_ids": P(None, "dp", "cp" if mm.cp > 1 else None),
        "target_ids": P(None, "dp", "cp" if mm.cp > 1 else None),
        "position_ids": P(None, "cp" if mm.cp > 1 else None),
    }
    m, _, s = ids.shape
    batch = {
        "input_ids": ids,
        "target_ids": targets,
        "position_ids": np.broadcast_to(
            np.arange(s, dtype=np.int32), (m, s)
        ).copy(),
    }
    from scaletorch_tpu.parallel.tensor_parallel import pvary_missing

    def mean_loss(p, b):
        axes = ("dp", "cp", "ep", "tp", "pp")
        return jax.lax.pmean(pvary_missing(pipe_loss(p, b), axes), axes)

    f = jax.jit(
        jax.value_and_grad(
            jax.shard_map(
                mean_loss, mesh=mm.mesh,
                in_specs=(p_specs, b_specs), out_specs=P(),
            )
        )
    )
    return f(params, batch)


@pytest.fixture(scope="module")
def setup():
    params = init_params(jax.random.PRNGKey(0), CFG)
    ids = jax.random.randint(jax.random.PRNGKey(1), (4, 4, 16), 0, CFG.vocab_size)
    targets = jax.random.randint(jax.random.PRNGKey(2), (4, 4, 16), 0, CFG.vocab_size)
    loss, grads = _golden(params, ids, targets)
    return params, ids, targets, loss, grads


class TestPipelineNumerics:
    @pytest.mark.parametrize("pp", [2, 4])
    def test_pp_matches_single_device(self, setup, pp):
        params, ids, targets, ref_loss, ref_grads = setup
        mm = MeshManager(pp=pp, dp=8 // pp)
        loss, grads = _pipeline(mm, params, ids, targets)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)
        # pipeline grads are per-parameter partials; only loss grads w.r.t.
        # full params compare (specs gather shards back automatically
        # outside shard_map)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=5e-4, atol=2e-5),
            grads, ref_grads,
        )

    def test_pp_with_tp(self, setup):
        params, ids, targets, ref_loss, ref_grads = setup
        mm = MeshManager(pp=2, tp=2, dp=2)
        loss, grads = _pipeline(mm, params, ids, targets)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=5e-4, atol=2e-5),
            grads, ref_grads,
        )

    def test_pp_with_tp_sp(self, setup):
        params, ids, targets, ref_loss, ref_grads = setup
        mm = MeshManager(pp=2, tp=2, dp=2)
        loss, grads = _pipeline(
            mm, params, ids, targets, sequence_parallel=True
        )
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=5e-4, atol=2e-5),
            grads, ref_grads,
        )

    def test_pp_gradient_checkpointing(self, setup):
        params, ids, targets, ref_loss, _ = setup
        mm = MeshManager(pp=2, dp=4)
        loss, _ = _pipeline(mm, params, ids, targets, gradient_checkpointing=True)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)


class TestPipelineTrainStep:
    @pytest.mark.parametrize("schedule", ["afab", "1f1b"])
    def test_spmd_step_with_pp(self, schedule):
        from scaletorch_tpu.config import ScaleTorchTPUArguments
        from scaletorch_tpu.parallel.spmd import make_spmd_train_step, shard_params
        from scaletorch_tpu.trainer.optimizer import create_optimizer

        mm = MeshManager(pp=2, tp=2, dp=2)
        params = init_params(jax.random.PRNGKey(0), CFG)
        tcfg = ScaleTorchTPUArguments(
            learning_rate=1e-3, total_train_steps=10, warmup_steps=0
        )
        tx, _ = create_optimizer(tcfg, include_clip=False)
        step_fn, p_specs, o_specs = make_spmd_train_step(
            mm, forward, CFG, tx, params,
            max_grad_norm=1.0, pp_schedule=schedule, donate=False,
        )
        params_s = shard_params(mm, params, p_specs)
        opt_state = shard_params(mm, tx.init(params), o_specs)

        rng = np.random.default_rng(0)
        accum, bsz, seq = 2, 2, 16
        ids = rng.integers(0, CFG.vocab_size, (accum, bsz, seq + 1))
        batch = {
            "input_ids": ids[:, :, :-1].astype(np.int32),
            "target_ids": ids[:, :, 1:].astype(np.int32),
            "position_ids": np.broadcast_to(
                np.arange(seq, dtype=np.int32), (accum, seq)
            ).copy(),
        }
        p2, o2, metrics = step_fn(params_s, opt_state, batch)
        assert np.isfinite(float(metrics["loss"]))
        assert np.isfinite(float(metrics["grad_norm"]))
        # params actually changed (compare against the host copy —
        # params_s was donated into the step)
        delta = jax.tree.map(
            lambda a, b: float(jnp.max(jnp.abs(jnp.asarray(a) - b))), p2, params
        )
        assert max(jax.tree.leaves(delta)) > 0
