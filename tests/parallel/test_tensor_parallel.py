"""TP/SP correctness: real shard_map collectives vs single-device golden.

The reference tests its TP autograd functions with mocked collectives
(tests/parallel/test_tp_comms.py); here the actual psum/all_gather/
psum_scatter run on the 8-virtual-device mesh and the whole TP model
forward/backward is checked against the pure single-device forward.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from scaletorch_tpu.models.layers import cross_entropy_loss
from scaletorch_tpu.models.llama import LlamaConfig, forward, init_params
from scaletorch_tpu.models.qwen3 import Qwen3Config
from scaletorch_tpu.parallel.mesh import MeshManager
from scaletorch_tpu.parallel.tensor_parallel import (
    column_parallel_linear,
    llama_param_specs,
    row_parallel_linear,
    validate_tp_divisibility,
    vocab_parallel_cross_entropy,
    vocab_parallel_embedding,
)

CFG = LlamaConfig(
    vocab_size=128, hidden_size=64, intermediate_size=128, num_hidden_layers=2,
    num_attention_heads=4, num_key_value_heads=4, dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def setup():
    params = init_params(jax.random.PRNGKey(0), CFG)
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, CFG.vocab_size)
    targets = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0, CFG.vocab_size)
    ref_logits = forward(params, ids, CFG)
    return params, ids, targets, ref_logits


class TestParallelLayers:
    def test_column_row_roundtrip(self):
        """column(x) -> row == full matmul chain."""
        mm = MeshManager(tp=4, dp=2)
        key = jax.random.PRNGKey(3)
        x = jax.random.normal(key, (2, 8, 16))
        w1 = jax.random.normal(jax.random.fold_in(key, 1), (16, 32))
        w2 = jax.random.normal(jax.random.fold_in(key, 2), (32, 16))
        ref = (x @ w1) @ w2

        def body(x, w1_l, w2_l):
            h = column_parallel_linear(x, w1_l)
            return row_parallel_linear(h, w2_l)

        f = jax.shard_map(
            body, mesh=mm.mesh,
            in_specs=(P(), P(None, "tp"), P("tp", None)),
            out_specs=P(),
        )
        np.testing.assert_allclose(f(x, w1, w2), ref, atol=1e-4)

    def test_vocab_parallel_embedding(self):
        mm = MeshManager(tp=4, dp=2)
        table = jax.random.normal(jax.random.PRNGKey(4), (64, 16))
        ids = jax.random.randint(jax.random.PRNGKey(5), (2, 8), 0, 64)
        f = jax.shard_map(
            lambda i, t: vocab_parallel_embedding(i, t),
            mesh=mm.mesh, in_specs=(P(), P("tp", None)), out_specs=P(),
        )
        np.testing.assert_allclose(f(ids, table), table[ids], atol=1e-6)

    @pytest.mark.slow  # the ignore_index variant + fused-CE tests keep quick coverage
    def test_vocab_parallel_cross_entropy(self):
        mm = MeshManager(tp=8)
        logits = jax.random.normal(jax.random.PRNGKey(6), (2, 8, 64))
        targets = jax.random.randint(jax.random.PRNGKey(7), (2, 8), 0, 64)
        ref = cross_entropy_loss(logits, targets)
        f = jax.shard_map(
            lambda l, t: vocab_parallel_cross_entropy(l, t),
            mesh=mm.mesh,
            in_specs=(P(None, None, "tp"), P()),
            out_specs=P(),
        )
        assert float(f(logits, targets)) == pytest.approx(float(ref), rel=1e-5)

    def test_vocab_parallel_ce_ignore_index(self):
        mm = MeshManager(tp=8)
        logits = jax.random.normal(jax.random.PRNGKey(8), (1, 6, 64))
        targets = jnp.array([[1, 2, -100, 40, -100, 63]])
        ref = cross_entropy_loss(logits, targets)
        f = jax.shard_map(
            lambda l, t: vocab_parallel_cross_entropy(l, t),
            mesh=mm.mesh, in_specs=(P(None, None, "tp"), P()), out_specs=P(),
        )
        assert float(f(logits, targets)) == pytest.approx(float(ref), rel=1e-5)


@pytest.mark.slow
class TestTpModelParity:
    @pytest.mark.parametrize("sp", [False, True], ids=["tp", "tp_sp"])
    def test_forward_matches_single_device(self, setup, sp):
        params, ids, _, ref_logits = setup
        mm = MeshManager(tp=4, dp=2)
        specs = llama_param_specs(CFG)
        f = jax.shard_map(
            lambda p, i: forward(p, i, CFG, tp_axis="tp", sequence_parallel=sp),
            mesh=mm.mesh, in_specs=(specs, P()), out_specs=P(None, None, "tp"),
        )
        np.testing.assert_allclose(f(params, ids), ref_logits, atol=3e-5)

    def test_gqa_tp2(self):
        """kv heads sharded too (2 kv heads over tp=2)."""
        cfg = LlamaConfig(
            vocab_size=128, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            dtype=jnp.float32,
        )
        params = init_params(jax.random.PRNGKey(0), cfg)
        ids = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0, 128)
        ref = forward(params, ids, cfg)
        mm = MeshManager(tp=2, dp=4)
        f = jax.shard_map(
            lambda p, i: forward(p, i, cfg, tp_axis="tp"),
            mesh=mm.mesh, in_specs=(llama_param_specs(cfg), P()),
            out_specs=P(None, None, "tp"),
        )
        np.testing.assert_allclose(f(params, ids), ref, atol=3e-5)

    def test_qwen3_qk_norm_tied_tp(self):
        cfg = Qwen3Config(
            vocab_size=128, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            head_dim=16, dtype=jnp.float32,
        )
        params = init_params(jax.random.PRNGKey(0), cfg)
        ids = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0, 128)
        ref = forward(params, ids, cfg)
        mm = MeshManager(tp=2, dp=4)
        f = jax.shard_map(
            lambda p, i: forward(p, i, cfg, tp_axis="tp", sequence_parallel=True),
            mesh=mm.mesh, in_specs=(llama_param_specs(cfg), P()),
            out_specs=P(None, None, "tp"),
        )
        np.testing.assert_allclose(f(params, ids), ref, atol=3e-5)

    @pytest.mark.slow
    def test_grads_match_single_device(self, setup):
        params, ids, targets, _ = setup
        mm = MeshManager(tp=4, dp=2)
        specs = llama_param_specs(CFG)

        def dense_loss(p):
            return cross_entropy_loss(forward(p, ids, CFG), targets)

        def tp_loss(p, i, t):
            logits = forward(p, i, CFG, tp_axis="tp", sequence_parallel=True)
            return vocab_parallel_cross_entropy(logits, t)

        g_ref = jax.grad(dense_loss)(params)
        g_tp = jax.shard_map(
            lambda p, i, t: jax.grad(tp_loss)(p, i, t),
            mesh=mm.mesh, in_specs=(specs, P(), P()), out_specs=specs,
        )(params, ids, targets)
        for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_tp)):
            np.testing.assert_allclose(a, b, atol=2e-5)


class TestValidation:
    def test_divisibility(self):
        validate_tp_divisibility(CFG, 4)
        with pytest.raises(ValueError, match="num_key_value_heads"):
            validate_tp_divisibility(
                LlamaConfig(num_key_value_heads=2, num_attention_heads=4,
                            intermediate_size=128, vocab_size=128), 4
            )


@pytest.mark.slow
class TestSpmdTrainStep:
    def test_dp_tp_sp_step_matches_single_device(self, setup):
        from scaletorch_tpu.config import ScaleTorchTPUArguments
        from scaletorch_tpu.parallel.spmd import make_spmd_train_step, shard_params
        from scaletorch_tpu.trainer.optimizer import create_optimizer
        from scaletorch_tpu.trainer.train_step import make_train_step

        params, *_ = setup
        args = ScaleTorchTPUArguments(
            total_train_steps=10, learning_rate=1e-3, max_grad_norm=1.0
        )
        tx_ref, _ = create_optimizer(args)
        ref_step = make_train_step(forward, CFG, tx_ref, donate=False)

        mm = MeshManager(dp=4, tp=2)
        tx, _ = create_optimizer(args, include_clip=False)
        step, p_specs, o_specs = make_spmd_train_step(
            mm, forward, CFG, tx, params,
            sequence_parallel=True, max_grad_norm=1.0, donate=False,
        )
        rng = np.random.default_rng(0)
        toks = rng.integers(0, 128, size=(2, 4, 17), dtype=np.int32)
        batch = {
            "input_ids": jnp.asarray(toks[:, :, :-1]),
            "target_ids": jnp.asarray(toks[:, :, 1:]),
            "position_ids": jnp.broadcast_to(
                jnp.arange(16, dtype=jnp.int32), (2, 16)
            ),
        }
        p1, _, m1 = ref_step(params, tx_ref.init(params), batch)
        p2, _, m2 = step(
            shard_params(mm, params, p_specs),
            shard_params(mm, tx.init(params), o_specs),
            batch,
        )
        assert float(m1["loss"]) == pytest.approx(float(m2["loss"]), rel=1e-5)
        assert float(m1["grad_norm"]) == pytest.approx(
            float(m2["grad_norm"]), rel=1e-4
        )
        for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(jax.device_get(p2))):
            np.testing.assert_allclose(a, b, atol=5e-5)
