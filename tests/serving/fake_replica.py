#!/usr/bin/env python
"""A jax-free replica double: scripted engine worker + the REAL wire.

The process-fleet tests (test_remote.py, test_supervisor.py) need child
processes that boot in milliseconds, stream deterministic tokens, obey
cancel/drain/stall, and die on command — without paying a jax import or
a compile per child. ``FakeEngineWorker`` is an ``EngineWorker``-shaped
double (same duck surface ``ReplicaServer`` documents); run as a script
this module is a drop-in stand-in for ``scripts/replica.py``: it binds
a real ``ReplicaServer``, prints ``READY port=<n>``, drains to exit 0
on SIGTERM, and honors the test-only crash hooks:

  --selfcrash_after_s S --selfcrash_code C   os._exit(C) S seconds
                                             after boot (deterministic
                                             crash-family exits without
                                             racing a kill -9)
  --token_delay_s D                          per-token decode latency
                                             (stretch streams so a test
                                             can kill mid-flight)

Token stream is a pure function of the prompt: token i is
``(sum(prompt) + i) % vocab`` — any observer can recompute the expected
stream, so conservation tests can also assert payload integrity.
"""

from __future__ import annotations

import argparse
import os
import sys
import threading
import time
from types import SimpleNamespace

sys.path.insert(
    0,
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))))


class FakeEngineWorker:
    """EngineWorker-shaped double: one thread per request, no jax.

    Matches the surface ``ReplicaServer`` (and the gateway dispatcher)
    relies on: ``submit``/``cancel``/``gauges``/``stall``/``alive``/
    ``inflight``/``page_size``/``shutdown``/``join``/``tick_listeners``.
    """

    def __init__(self, *, token_delay_s: float = 0.005,
                 vocab: int = 101, page_size: int = 16,
                 page_pool: int = 32) -> None:
        self.alive = True
        self.exit_code = None
        self.page_size = page_size
        self.page_pool = page_pool
        self.vocab = vocab
        self.token_delay_s = token_delay_s
        self.tick_listeners = []
        self.draining = False
        self._stall_until = 0.0
        self._lock = threading.Lock()
        self._next_id = 0
        self._live = set()
        self._cancelled = {}

    # -- observability ------------------------------------------------------
    @property
    def inflight(self) -> int:
        with self._lock:
            return len(self._live)

    def gauges(self):
        with self._lock:
            live = len(self._live)
        return {
            "queue_depth": 0.0,
            "slot_occupancy": live / 4.0,
            "pages_in_use": float(live),
            "page_pool_free": float(self.page_pool - live),
        }

    # -- control ------------------------------------------------------------
    def stall(self, seconds: float) -> None:
        self._stall_until = time.monotonic() + seconds

    def cancel(self, request_id: int, detail: str) -> None:
        with self._lock:
            if request_id in self._live:
                self._cancelled[request_id] = detail

    def shutdown(self, *, drain: bool = True) -> None:
        self.draining = True

    def join(self, timeout=None) -> None:
        deadline = (time.monotonic() + timeout) if timeout else None
        while self.inflight > 0 and (
                deadline is None or time.monotonic() < deadline):
            time.sleep(0.005)

    def expected_tokens(self, prompt, n):
        base = sum(prompt) % self.vocab
        return [(base + i) % self.vocab for i in range(n)]

    # -- the request path ---------------------------------------------------
    def submit(self, req, on_tokens, on_done, *, ttl_s=None,
               on_submitted=None) -> None:
        with self._lock:
            rid = self._next_id
            self._next_id += 1
            self._live.add(rid)
        threading.Thread(
            target=self._run,
            args=(rid, req, on_tokens, on_done, on_submitted),
            name=f"fake-req-{rid}", daemon=True).start()

    def _run(self, rid, req, on_tokens, on_done, on_submitted) -> None:
        if on_submitted is not None:
            on_submitted(rid)
        tokens = []
        outcome, reason, detail = "ok", "length", None
        for tok in self.expected_tokens(req.prompt, req.max_new_tokens):
            while time.monotonic() < self._stall_until:
                time.sleep(0.01)
            time.sleep(self.token_delay_s)
            with self._lock:
                cancel_detail = self._cancelled.pop(rid, None)
            if cancel_detail is not None:
                outcome, reason, detail = "aborted", "aborted", cancel_detail
                break
            tokens.append(tok)
            on_tokens(rid, [tok])
            if req.eos_id is not None and tok == req.eos_id:
                reason = "eos"
                break
        with self._lock:
            self._live.discard(rid)
            self._cancelled.pop(rid, None)
        on_done(SimpleNamespace(
            request_id=rid, prompt=list(req.prompt), tokens=tokens,
            finish_reason=reason, outcome=outcome, detail=detail,
            ttft_s=None, latency_s=None, queue_wait_s=0.0,
            prefill_s=0.0, prefix_hit=False, trace_id=req.trace_id))


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--replica_id", default="r0")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--token_delay_s", type=float, default=0.005)
    p.add_argument("--drain_timeout_s", type=float, default=10.0)
    p.add_argument("--selfcrash_after_s", type=float, default=0.0)
    p.add_argument("--selfcrash_code", type=int, default=42)
    return p.parse_args(argv)


async def _serve(args, worker) -> None:
    import asyncio
    import signal

    from scaletorch_tpu.serving.remote import ReplicaServer

    server = ReplicaServer(worker, host=args.host, port=args.port)
    await server.start()
    print(f"READY port={server.port}", flush=True)
    if args.selfcrash_after_s > 0:
        # armed AFTER READY so the crash clock never races the boot
        timer = threading.Timer(
            args.selfcrash_after_s,
            lambda: os._exit(args.selfcrash_code))
        timer.daemon = True
        timer.start()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        loop.add_signal_handler(sig, server.request_drain)
    await server.wait_drain()
    worker.shutdown(drain=True)
    deadline = time.monotonic() + args.drain_timeout_s
    while worker.inflight > 0 and time.monotonic() < deadline:
        await asyncio.sleep(0.01)
    await server.close()


def main(argv=None) -> int:
    import asyncio

    args = parse_args(argv)
    worker = FakeEngineWorker(token_delay_s=args.token_delay_s)
    asyncio.run(_serve(args, worker))
    return 0


if __name__ == "__main__":
    sys.exit(main())
