#!/usr/bin/env python
"""A jax-free replica double: scripted engine worker + the REAL wire.

The process-fleet tests (test_remote.py, test_supervisor.py) need child
processes that boot in milliseconds, stream deterministic tokens, obey
cancel/drain/stall, and die on command — without paying a jax import or
a compile per child. ``FakeEngineWorker`` is an ``EngineWorker``-shaped
double (same duck surface ``ReplicaServer`` documents); run as a script
this module is a drop-in stand-in for ``scripts/replica.py``: it binds
a real ``ReplicaServer``, prints ``READY port=<n>``, drains to exit 0
on SIGTERM, and honors the test-only crash hooks:

  --selfcrash_after_s S --selfcrash_code C   os._exit(C) S seconds
                                             after boot (deterministic
                                             crash-family exits without
                                             racing a kill -9)
  --token_delay_s D                          per-token decode latency
                                             (stretch streams so a test
                                             can kill mid-flight)

Token stream is a pure function of the prompt: token i is
``(sum(prompt) + i) % vocab`` — any observer can recompute the expected
stream, so conservation tests can also assert payload integrity.
"""

from __future__ import annotations

import argparse
import os
import sys
import threading
import time
from types import SimpleNamespace

sys.path.insert(
    0,
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))))


class FakeEngineWorker:
    """EngineWorker-shaped double: one thread per request, no jax.

    Matches the surface ``ReplicaServer`` (and the gateway dispatcher)
    relies on: ``submit``/``cancel``/``gauges``/``stall``/``alive``/
    ``inflight``/``page_size``/``shutdown``/``join``/``tick_listeners``.
    """

    def __init__(self, *, token_delay_s: float = 0.005,
                 vocab: int = 101, page_size: int = 16,
                 page_pool: int = 32) -> None:
        self.alive = True
        self.exit_code = None
        self.page_size = page_size
        self.page_pool = page_pool
        self.vocab = vocab
        self.token_delay_s = token_delay_s
        self.tick_listeners = []
        self.draining = False
        self._stall_until = 0.0
        self._lock = threading.Lock()
        self._next_id = 0
        self._live = set()
        self._cancelled = {}
        # warm-rejoin double state: (tokens, pages) chains plus
        # per-page byte contents, same duck surface the real
        # EngineWorker bridges to the engine
        self.warm_pages_total = 0
        self._chains = []
        self._page_contents = {}
        self._next_page = 0

    # -- observability ------------------------------------------------------
    @property
    def inflight(self) -> int:
        with self._lock:
            return len(self._live)

    def gauges(self):
        with self._lock:
            live = len(self._live)
            prefix_pages = len(self._page_contents)
            warm = self.warm_pages_total
        return {
            "queue_depth": 0.0,
            "slot_occupancy": live / 4.0,
            "pages_in_use": float(live),
            "page_pool_free": float(self.page_pool - live),
            "prefix_pages": float(prefix_pages),
            "warm_pages_total": float(warm),
            "decode_compile_count": 1.0,
        }

    # -- control ------------------------------------------------------------
    def stall(self, seconds: float) -> None:
        self._stall_until = time.monotonic() + seconds

    def cancel(self, request_id: int, detail: str) -> None:
        with self._lock:
            if request_id in self._live:
                self._cancelled[request_id] = detail

    def shutdown(self, *, drain: bool = True) -> None:
        self.draining = True

    def join(self, timeout=None) -> None:
        deadline = (time.monotonic() + timeout) if timeout else None
        while self.inflight > 0 and (
                deadline is None or time.monotonic() < deadline):
            time.sleep(0.005)

    def expected_tokens(self, prompt, n):
        base = sum(prompt) % self.vocab
        return [(base + i) % self.vocab for i in range(n)]

    # -- warm-rejoin surface (prefix_map / export / import) -----------------
    @staticmethod
    def page_bytes(page: int, nbytes: int):
        """Deterministic (k, v) contents for a page id — any observer
        can recompute them, so transfer tests assert bit-parity."""
        k = bytes((page * 31 + i) % 256 for i in range(nbytes))
        v = bytes((page * 37 + i + 1) % 256 for i in range(nbytes))
        return k, v

    def seed_prefix(self, tokens) -> int:
        """Register a frozen prefix chain (complete pages only) with
        deterministic contents; returns the number of pages seeded."""
        n = len(tokens) // self.page_size
        if n == 0:
            return 0
        with self._lock:
            pages = list(range(self._next_page, self._next_page + n))
            self._next_page += n
            for p in pages:
                self._page_contents[p] = self.page_bytes(p, self.page_size)
            self._chains.append((list(tokens[:n * self.page_size]), pages))
        return n

    def prefix_map(self):
        with self._lock:
            chains = [{"tokens": list(t), "pages": list(p)}
                      for t, p in self._chains]
            pages = {p: {"refcount": 1, "frozen": True}
                     for p in self._page_contents}
            used = len(self._page_contents)
        return {
            "page_size": self.page_size,
            "dtype": "uint8",
            "page_shape": [1, 1, self.page_size, 1],
            "chains": chains,
            "pages": pages,
            "capacity": self.page_pool,
            "free": self.page_pool - used,
        }

    def export_prefix_pages(self, pages):
        meta = {"dtype": "uint8",
                "page_shape": [1, 1, self.page_size, 1],
                "page_size": self.page_size}
        with self._lock:
            contents = {int(p): self._page_contents[int(p)]
                        for p in pages if int(p) in self._page_contents}
        return meta, contents

    def import_prefix_pages(self, chains, contents, *, dtype,
                            page_shape, page_size) -> dict:
        if page_size != self.page_size or dtype != "uint8":
            return {"pages": 0, "chains": []}
        created, kept = 0, []
        with self._lock:
            mapped = {}
            for tokens, pages in chains:
                valid = 0
                for p in pages:
                    if int(p) in mapped or int(p) in contents:
                        valid += 1
                    else:
                        break
                if valid == 0:
                    continue
                local = []
                for p in pages[:valid]:
                    p = int(p)
                    if p not in mapped:
                        mapped[p] = self._next_page
                        self._next_page += 1
                        self._page_contents[mapped[p]] = contents[p]
                        created += 1
                    local.append(mapped[p])
                tokens = list(tokens[:valid * self.page_size])
                self._chains.append((tokens, local))
                kept.append(tokens)
            self.warm_pages_total += created
        return {"pages": created, "chains": kept}

    def _has_warm_prefix(self, prompt) -> bool:
        with self._lock:
            return any(len(t) <= len(prompt)
                       and list(prompt[:len(t)]) == t
                       for t, _ in self._chains if t)

    # -- the request path ---------------------------------------------------
    def submit(self, req, on_tokens, on_done, *, ttl_s=None,
               on_submitted=None) -> None:
        with self._lock:
            rid = self._next_id
            self._next_id += 1
            self._live.add(rid)
        threading.Thread(
            target=self._run,
            args=(rid, req, on_tokens, on_done, on_submitted),
            name=f"fake-req-{rid}", daemon=True).start()

    def _run(self, rid, req, on_tokens, on_done, on_submitted) -> None:
        if on_submitted is not None:
            on_submitted(rid)
        tokens = []
        outcome, reason, detail = "ok", "length", None
        for tok in self.expected_tokens(req.prompt, req.max_new_tokens):
            while time.monotonic() < self._stall_until:
                time.sleep(0.01)
            time.sleep(self.token_delay_s)
            with self._lock:
                cancel_detail = self._cancelled.pop(rid, None)
            if cancel_detail is not None:
                outcome, reason, detail = "aborted", "aborted", cancel_detail
                break
            tokens.append(tok)
            on_tokens(rid, [tok])
            if req.eos_id is not None and tok == req.eos_id:
                reason = "eos"
                break
        with self._lock:
            self._live.discard(rid)
            self._cancelled.pop(rid, None)
        on_done(SimpleNamespace(
            request_id=rid, prompt=list(req.prompt), tokens=tokens,
            finish_reason=reason, outcome=outcome, detail=detail,
            ttft_s=None, latency_s=None, queue_wait_s=0.0,
            prefill_s=0.0, prefix_hit=self._has_warm_prefix(req.prompt),
            trace_id=req.trace_id))


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--replica_id", default="r0")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--token_delay_s", type=float, default=0.005)
    p.add_argument("--drain_timeout_s", type=float, default=10.0)
    p.add_argument("--selfcrash_after_s", type=float, default=0.0)
    p.add_argument("--selfcrash_code", type=int, default=42)
    p.add_argument("--uds", default="",
                   help="Bind a unix-domain socket instead of TCP; "
                        "READY then prints 'READY uds=<path>'.")
    p.add_argument("--warm_chain", default="",
                   help="Comma-separated tokens to seed as a frozen "
                        "prefix chain (complete pages only) so this "
                        "fake can DONATE warm state.")
    p.add_argument("--page_size", type=int, default=4)
    p.add_argument("--ft_gw_warm_donor_crash_at", type=int, default=0)
    p.add_argument("--ft_gw_warm_corrupt_chunk_at", type=int, default=0)
    return p.parse_args(argv)


async def _serve(args, worker) -> None:
    import asyncio
    import signal

    from scaletorch_tpu.inference.resilience import ServingFaultInjector
    from scaletorch_tpu.serving.remote import ReplicaServer

    injector = ServingFaultInjector.from_config(args)
    server = ReplicaServer(
        worker, host=args.host, port=args.port,
        uds=args.uds or None,
        injector=injector if injector.active else None)
    await server.start()
    if args.uds:
        print(f"READY uds={args.uds}", flush=True)
    else:
        print(f"READY port={server.port}", flush=True)
    if args.selfcrash_after_s > 0:
        # armed AFTER READY so the crash clock never races the boot
        timer = threading.Timer(
            args.selfcrash_after_s,
            lambda: os._exit(args.selfcrash_code))
        timer.daemon = True
        timer.start()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        loop.add_signal_handler(sig, server.request_drain)
    await server.wait_drain()
    worker.shutdown(drain=True)
    deadline = time.monotonic() + args.drain_timeout_s
    while worker.inflight > 0 and time.monotonic() < deadline:
        await asyncio.sleep(0.01)
    await server.close()


def main(argv=None) -> int:
    import asyncio

    args = parse_args(argv)
    worker = FakeEngineWorker(token_delay_s=args.token_delay_s,
                              page_size=args.page_size)
    if args.warm_chain:
        worker.seed_prefix(
            [int(t) for t in args.warm_chain.split(",") if t.strip()])
    asyncio.run(_serve(args, worker))
    return 0


if __name__ == "__main__":
    sys.exit(main())
