"""Tenant-fair admission: WFQ share property, rate limits, shed gates.

The fairness property (ISSUE acceptance): under a tenant storm the
victim tenant's service share stays within its WFQ weight, while FIFO
on the same arrival schedule starves it. Pure host-side with a fake
clock — no engine, deterministic, fast.
"""

import random

import pytest

from scaletorch_tpu.serving.admission import (
    AdmissionController,
    TenantConfig,
    TokenBucket,
    WeightedFairQueue,
    parse_tenant_spec,
)


class FakeClock:
    def __init__(self, t: float = 0.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t


class TestTenantSpec:
    def test_parses_grammar(self):
        cfgs = parse_tenant_spec("free:1:100:200, pro:4, batch:0.5")
        assert cfgs["free"].weight == 1.0
        assert cfgs["free"].rate == 100.0
        assert cfgs["free"].burst == 200.0
        assert cfgs["pro"].weight == 4.0
        assert cfgs["pro"].rate == 0.0
        assert cfgs["batch"].weight == 0.5

    @pytest.mark.parametrize("spec, match", [
        ("a:0", "weight"),
        ("a:1:-1", "rate"),
        (":1", "empty name"),
        ("a:x", "numbers"),
        ("a:1,a:2", "twice"),
        ("a:1:2:3:4", "expected"),
    ])
    def test_rejects_bad_specs(self, spec, match):
        with pytest.raises(ValueError, match=match):
            parse_tenant_spec(spec)


class TestTokenBucket:
    def test_rate_and_retry_after(self):
        clock = FakeClock()
        bucket = TokenBucket(rate=10.0, burst=20.0, clock=clock)
        ok, _ = bucket.try_take(20.0)
        assert ok
        ok, retry = bucket.try_take(10.0)
        assert not ok and retry == pytest.approx(1.0)
        clock.t += 1.0   # 10 units refill
        ok, _ = bucket.try_take(10.0)
        assert ok

    def test_zero_rate_is_unlimited(self):
        bucket = TokenBucket(rate=0.0, burst=0.0, clock=FakeClock())
        for _ in range(100):
            ok, _ = bucket.try_take(1e9)
            assert ok

    def test_burst_defaults_to_one_second_of_rate(self):
        bucket = TokenBucket(rate=5.0, burst=0.0, clock=FakeClock())
        ok, _ = bucket.try_take(5.0)
        assert ok
        ok, _ = bucket.try_take(0.1)
        assert not ok

    def test_cost_beyond_burst_is_never_grantable(self):
        """A cost deeper than the bucket can never be granted — the
        signal is `inf`, which admission turns into a terminal
        `rejected` (503) instead of a retry-forever 429."""
        bucket = TokenBucket(rate=100.0, burst=200.0, clock=FakeClock())
        ok, retry = bucket.try_take(300.0)
        assert not ok and retry == float("inf")
        ctrl = AdmissionController(
            gauges_fn=lambda: {},
            tenants={"free": TenantConfig("free", weight=1.0, rate=100.0,
                                          burst=200.0)},
            clock=FakeClock())
        decision = ctrl.offer("free", 1, 300.0)
        assert decision is not None
        assert decision.outcome == "rejected"
        assert "burst capacity" in decision.reason
        # a grantable cost still sheds with a finite Retry-After
        assert ctrl.offer("free", 2, 150.0) is None
        decision = ctrl.offer("free", 3, 150.0)
        assert decision is not None and decision.outcome == "shed"
        assert decision.retry_after_s < float("inf")


class TestWFQFairness:
    def _service_order(self, q, n):
        out = []
        for _ in range(n):
            entry = q.pop()
            if entry is None:
                break
            out.append(entry[0])
        return out

    def test_equal_weights_interleave_under_storm(self):
        """Storm tenant floods 100 requests before the victim's 10; the
        victim still receives ~its share of the next service slots —
        FIFO on the same schedule would serve the entire storm first."""
        q = WeightedFairQueue(clock=FakeClock())
        for i in range(100):
            q.push("storm", f"s{i}", 10.0)
        for i in range(10):
            q.push("victim", f"v{i}", 10.0)
        first20 = self._service_order(q, 20)
        # FIFO baseline: arrival order serves storm[0:20], victim share 0
        assert first20.count("victim") >= 8
        assert first20.count("storm") >= 8

    def test_share_tracks_weight_property(self):
        """Property over randomized storm schedules: with weights 3:1
        the heavy tenant gets ~3x the service of the light one while
        both stay backlogged (within 15% tolerance)."""
        for seed in range(4):
            rng = random.Random(seed)
            q = WeightedFairQueue(
                tenants={"heavy": TenantConfig("heavy", weight=3.0),
                         "light": TenantConfig("light", weight=1.0)},
                clock=FakeClock())
            # both tenants keep deep backlogs; arrival order shuffled
            pushes = (["heavy"] * 120) + (["light"] * 120)
            rng.shuffle(pushes)
            for i, tenant in enumerate(pushes):
                q.push(tenant, i, float(rng.randint(5, 15)))
            served = self._service_order(q, 120)
            heavy_share = served.count("heavy") / len(served)
            assert 0.75 - 0.15 <= heavy_share <= 0.75 + 0.15, \
                f"seed {seed}: heavy share {heavy_share}"

    def test_idle_tenant_pays_no_history(self):
        """A tenant that was idle while others consumed service starts
        at the CURRENT virtual time — it does not get unbounded credit
        (which would starve everyone) nor a penalty."""
        q = WeightedFairQueue(clock=FakeClock())
        for i in range(50):
            q.push("busy", f"b{i}", 10.0)
        for _ in range(40):
            q.pop()
        q.push("late", "l0", 10.0)
        # the late arrival lands within a couple of pops, not after the
        # whole remaining backlog
        next_three = self._service_order(q, 3)
        assert "late" in next_three

    def test_push_front_preserves_position(self):
        q = WeightedFairQueue(clock=FakeClock())
        q.push("a", "a0", 10.0)
        q.push("b", "b0", 10.0)
        tenant, item, cost = q.pop()
        q.push_front(tenant, item, cost)
        assert q.pop()[1] == item  # still at the head of fair order

    def test_unconfigured_tenant_state_is_bounded(self):
        """Tenant names are untrusted client strings: a client rotating
        random tenants must not grow the queue map without bound —
        drained unconfigured tenants are evicted, and an arrival that
        is shed before queueing creates no state at all."""
        q = WeightedFairQueue(
            tenants={"pro": TenantConfig("pro", weight=2.0)},
            clock=FakeClock())
        for i in range(1000):
            name = f"rotating-{i}"
            assert q.rate_check(name, 5.0) == (True, 0.0)  # stateless
            q.push(name, i, 5.0)
        while q.pop() is not None:
            pass
        q.push("pro", "keep", 5.0)
        q.pop()
        assert len(q._tenants) <= 1  # only the configured tenant may stay

    def test_depths_by_tenant(self):
        q = WeightedFairQueue(clock=FakeClock())
        q.push("a", 1, 1.0)
        q.push("a", 2, 1.0)
        q.push("b", 3, 1.0)
        assert q.depths() == {"a": 2, "b": 1}
        assert len(q) == 3


class TestAdmissionController:
    def _controller(self, gauges, **kw):
        return AdmissionController(gauges_fn=lambda: gauges, **kw)

    def test_backlog_cap_sheds_with_retry_after(self):
        ctrl = self._controller(
            {"queue_depth": 99.0, "num_slots": 1.0}, max_backlog=4)
        for i in range(4):
            assert ctrl.offer("t", i, 10.0) is None
        decision = ctrl.offer("t", 99, 10.0)
        assert decision is not None
        assert "capacity" in decision.reason
        assert decision.retry_after_s >= 1.0
        assert ctrl.shed_count == 1

    def test_full_backlog_evicts_over_share_tenant_for_victim(self):
        """The flooder cannot lock the victim out of the queue: a full
        backlog sheds the OVER-SHARE tenant's oldest request to admit
        an under-share arrival (PR 7's oldest-first shed, tenant-fair)."""
        evicted = []
        ctrl = AdmissionController(
            gauges_fn=lambda: {"queue_depth": 99.0, "num_slots": 1.0},
            max_backlog=4,
            on_shed=lambda item, decision: evicted.append(
                (item, decision.reason)))
        for i in range(4):
            assert ctrl.offer("flood", f"f{i}", 10.0) is None
        # the flooder's 5th arrival sheds (it is the over-share tenant)
        assert ctrl.offer("flood", "f4", 10.0) is not None
        assert evicted == []
        # the victim's arrival evicts the flooder's OLDEST instead
        assert ctrl.offer("victim", "v0", 10.0) is None
        assert [item for item, _ in evicted] == ["f0"]
        assert "fairness" in evicted[0][1]
        assert ctrl.queue.depths() == {"flood": 3, "victim": 1}

    def test_rate_limit_sheds(self):
        clock = FakeClock()
        ctrl = AdmissionController(
            gauges_fn=lambda: {},
            tenants={"t": TenantConfig("t", weight=1.0, rate=10.0,
                                       burst=10.0)},
            clock=clock)
        assert ctrl.offer("t", 1, 10.0) is None
        decision = ctrl.offer("t", 2, 10.0)
        assert decision is not None and "rate limit" in decision.reason
        assert decision.retry_after_s > 0

    def test_pool_saturation_sheds_only_with_backlog(self):
        gauges = {"pages_in_use": 99.0, "page_pool_free": 1.0,
                  "queue_depth": 99.0, "num_slots": 1.0}
        ctrl = self._controller(gauges, free_page_watermark=0.10)
        # empty backlog: the first arrival queues even with a hot pool
        assert ctrl.offer("t", 1, 10.0) is None
        # standing backlog + saturated pool: shed
        decision = ctrl.offer("t", 2, 10.0)
        assert decision is not None and "watermark" in decision.reason

    def test_dense_layout_has_no_pool_gate(self):
        gauges = {"pages_in_use": 0.0, "page_pool_free": 0.0,
                  "queue_depth": 99.0, "num_slots": 1.0}
        ctrl = self._controller(gauges, free_page_watermark=0.5)
        assert ctrl.offer("t", 1, 10.0) is None
        assert ctrl.offer("t", 2, 10.0) is None

    def test_dispatch_gated_on_engine_queue_depth(self):
        gauges = {"queue_depth": 0.0, "num_slots": 2.0}
        ctrl = self._controller(gauges)
        ctrl.offer("t", "item", 10.0)
        assert ctrl.next_ready() == ("t", "item", 10.0)
        gauges["queue_depth"] = 2.0   # engine queue at num_slots: hold
        ctrl.offer("t", "item2", 10.0)
        assert ctrl.next_ready() is None
        gauges["queue_depth"] = 1.0
        assert ctrl.next_ready()[1] == "item2"
