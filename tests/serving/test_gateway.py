"""Gateway end-to-end over real HTTP: SSE bit-parity, conservation,
fairness, drills, drain.

Quick tier, CPU. Each test boots a real ``ServingGateway`` (ephemeral
port, background event-loop thread) over real tiny-Llama engines and
talks to it with urllib / raw sockets — the full stack a production
client would traverse, minus only the network.
"""

import json
import socket
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from scaletorch_tpu.inference import (
    InferenceEngine,
    SamplingParams,
    ServingFaultInjector,
)
from scaletorch_tpu.models import llama
from scaletorch_tpu.serving.admission import TenantConfig
from scaletorch_tpu.serving.gateway import ServingGateway
from scaletorch_tpu.serving.protocol import (
    STATUS_BY_OUTCOME,
    parse_sse_stream,
    stream_tokens,
)
from scaletorch_tpu.telemetry.export import TelemetryExporter, read_jsonl

TINY = dict(
    vocab_size=64, hidden_size=32, intermediate_size=64,
    num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
    dtype=jnp.float32,
)
PAGE = 4


@pytest.fixture(scope="module")
def tiny_llama():
    cfg = llama.LlamaConfig(**TINY)
    return cfg, llama.init_params(jax.random.PRNGKey(0), cfg)


def make_engine(tiny_llama, **kw):
    cfg, params = tiny_llama
    kw.setdefault("max_slots", 2)
    kw.setdefault("max_seq", 32)
    kw.setdefault("prefill_len", 16)
    kw.setdefault("sampling", SamplingParams(temperature=0.0))
    kw.setdefault("cache_layout", "paged")
    kw.setdefault("page_size", PAGE)
    kw.setdefault("strict_submit", False)
    return InferenceEngine(params, cfg, **kw)


def post(port, body, *, timeout=60, headers=()):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/generate",
        data=json.dumps(body).encode(), method="POST")
    for k, v in headers:
        req.add_header(k, v)
    try:
        resp = urllib.request.urlopen(req, timeout=timeout)
        return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as err:
        return err.code, dict(err.headers), err.read()


def get(port, path, timeout=30):
    try:
        resp = urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout)
        return resp.status, resp.read()
    except urllib.error.HTTPError as err:
        return err.code, err.read()


def ref_tokens(tiny_llama, prompt, n):
    """Direct-engine greedy oracle (no gateway)."""
    eng = make_engine(tiny_llama)
    rid = eng.submit(prompt, max_new_tokens=n)
    return eng.run()[rid].tokens


class TestStreamingParity:
    def test_sse_stream_bit_identical_and_one_compile(self, tiny_llama):
        """Acceptance: SSE-streamed tokens == direct engine bit-for-bit
        and the bridge adds zero retraces."""
        engine = make_engine(tiny_llama)
        gw = ServingGateway(engine, port=0).start_in_thread()
        try:
            prompts = [[1, 2, 3], [7, 8, 9, 10], [4, 4, 4]]
            for prompt in prompts:
                status, _, raw = post(
                    gw.port,
                    {"prompt": prompt, "max_new_tokens": 6, "stream": True})
                assert status == 200
                events = parse_sse_stream(raw)
                dones = [d for e, d in events if e == "done"]
                assert len(dones) == 1, events
                assert dones[0]["outcome"] == "ok"
                streamed = stream_tokens(events)
                assert streamed == dones[0]["token_ids"]
                assert streamed == ref_tokens(tiny_llama, prompt, 6)
            assert engine.decode_compile_count == 1
            assert engine.prefill_compile_count == 1
        finally:
            gw.stop_sync()
        gw.metrics.check_conservation()

    def test_unary_response_and_usage(self, tiny_llama):
        gw = ServingGateway(make_engine(tiny_llama),
                            port=0).start_in_thread()
        try:
            status, _, raw = post(
                gw.port, {"prompt": [5, 6], "max_new_tokens": 4,
                          "stream": False})
            assert status == 200
            payload = json.loads(raw)
            assert payload["outcome"] == "ok"
            assert payload["finish_reason"] == "length"
            assert payload["token_ids"] == ref_tokens(tiny_llama, [5, 6], 4)
            assert payload["usage"] == {"prompt_tokens": 2,
                                       "completion_tokens": 4}
        finally:
            gw.stop_sync()

    def test_malformed_request_is_400_rejected(self, tiny_llama):
        gw = ServingGateway(make_engine(tiny_llama),
                            port=0).start_in_thread()
        try:
            status, _, raw = post(gw.port, {"prompt": []})
            assert status == 400
            assert json.loads(raw)["outcome"] == "rejected"
            status, _, _ = post(
                gw.port, {"prompt": [1] * 500, "stream": False})
            assert status == 503  # over prefill_len: engine rejects
            assert gw.metrics.outcomes["rejected"] == 2
        finally:
            gw.stop_sync()
        gw.metrics.check_conservation()


class TestObservability:
    def test_healthz_metrics_and_jsonl_parity(self, tiny_llama, tmp_path):
        exporter = TelemetryExporter(str(tmp_path / "gw.jsonl"))
        gw = ServingGateway(
            make_engine(tiny_llama), port=0, exporter=exporter,
            export_every=1).start_in_thread()
        try:
            status, raw = get(gw.port, "/healthz")
            assert status == 200
            health = json.loads(raw)
            assert health["status"] == "ok"
            assert health["replicas"]["r0"]["alive"] is True
            assert "page_pool_free" in health["replicas"]["r0"]

            post(gw.port, {"prompt": [1, 2], "max_new_tokens": 2,
                           "stream": False})
            status, raw = get(gw.port, "/metrics")
            assert status == 200
            text = raw.decode()
            for needle in (
                "scaletorch_http_requests_received",
                "scaletorch_sse_streams_open",
                "scaletorch_gateway_shed_total",
                "scaletorch_router_prefix_route_rate",
                # replica identity rides a LABEL, not the metric name
                'scaletorch_engine_pages_in_use{replica="r0"}',
                'scaletorch_engine_queue_depth{replica="r0"}',
                # tenant-labeled latency histograms: real histogram
                # TYPE with _bucket/_sum/_count and an le label
                "# TYPE scaletorch_request_ttft_seconds histogram",
                'scaletorch_request_ttft_seconds_bucket{le=',
                'scaletorch_request_ttft_seconds_count{tenant="default"} 1',
                'scaletorch_request_e2e_seconds_sum{tenant="default"}',
                'scaletorch_request_queue_wait_seconds_count'
                '{tenant="default"} 1',
            ):
                assert needle in text, f"missing {needle}"
        finally:
            gw.stop_sync()
        exporter.close()
        events = read_jsonl(str(tmp_path / "gw.jsonl"))
        assert events, "no gateway_metrics records exported"
        by_kind = {}
        for event in events:
            assert event["v"] == 1
            by_kind.setdefault(event["kind"], []).append(event)
        for event in by_kind["gateway_metrics"]:
            assert "http_requests_received" in event
        assert by_kind["gateway_metrics"][-1]["http_ok"] == 1
        # one access record per terminal HTTP outcome
        access = by_kind["access"]
        assert len(access) == 1
        rec = access[0]
        assert rec["tenant"] == "default"
        assert rec["outcome"] == "ok" and rec["status"] == 200
        assert rec["replica"] == "r0"
        assert rec["tokens"] == 2 and rec["prompt_tokens"] == 2
        assert isinstance(rec["trace_id"], str) and len(rec["trace_id"]) == 32
        assert rec["ttft_s"] > 0 and rec["e2e_s"] >= rec["ttft_s"]
        assert rec["queue_wait_s"] >= 0
        assert rec["prefix_hit"] is False
        # the mergeable per-tenant histogram state rode the same stream
        assert "latency_histograms" in by_kind

    def test_404_and_405(self, tiny_llama):
        gw = ServingGateway(make_engine(tiny_llama),
                            port=0).start_in_thread()
        try:
            assert get(gw.port, "/nope")[0] == 404
            # malformed framing is a CLIENT error, never a logged 500
            sock = socket.create_connection(("127.0.0.1", gw.port),
                                            timeout=30)
            sock.sendall(b"POST /v1/generate HTTP/1.1\r\nHost: x\r\n"
                         b"Content-Length: abc\r\n\r\n")
            reply = b""
            while True:
                chunk = sock.recv(4096)
                if not chunk:
                    break
                reply += chunk
            sock.close()
            assert reply.startswith(b"HTTP/1.1 400"), reply[:60]
            req = urllib.request.Request(
                f"http://127.0.0.1:{gw.port}/v1/generate", method="GET")
            try:
                status = urllib.request.urlopen(req, timeout=30).status
            except urllib.error.HTTPError as err:
                status = err.code
            assert status == 405
        finally:
            gw.stop_sync()


class TestKeepAlive:
    """ROADMAP front-door item: scrape-heavy Prometheus consumers must
    not pay a TCP connection per scrape — GET /metrics and /healthz
    hold the connection open (HTTP/1.1 keep-alive) until the client
    says Connection: close."""

    @staticmethod
    def _get_on(sock, path, close=False):
        extra = "Connection: close\r\n" if close else ""
        sock.sendall(
            f"GET {path} HTTP/1.1\r\nHost: x\r\n{extra}\r\n".encode())
        buf = b""
        while b"\r\n\r\n" not in buf:
            chunk = sock.recv(4096)
            assert chunk, f"connection closed mid-response: {buf!r}"
            buf += chunk
        head, _, body = buf.partition(b"\r\n\r\n")
        headers = head.decode().split("\r\n")
        length = next(int(h.split(":", 1)[1]) for h in headers
                      if h.lower().startswith("content-length"))
        while len(body) < length:
            chunk = sock.recv(4096)
            assert chunk, "connection closed mid-body"
            body += chunk
        return headers, body[:length]

    def test_scrape_connection_reuse(self, tiny_llama):
        gw = ServingGateway(make_engine(tiny_llama),
                            port=0).start_in_thread()
        try:
            sock = socket.create_connection(("127.0.0.1", gw.port),
                                            timeout=30)
            try:
                # three requests over ONE connection, mixed endpoints
                for path in ("/metrics", "/healthz", "/metrics"):
                    headers, body = self._get_on(sock, path)
                    assert headers[0].startswith("HTTP/1.1 200"), headers
                    assert any("connection: keep-alive" in h.lower()
                               for h in headers), headers
                    assert body
                # Connection: close is honored: response says close and
                # the server actually closes
                headers, _ = self._get_on(sock, "/healthz", close=True)
                assert any("connection: close" in h.lower()
                           for h in headers), headers
                sock.settimeout(10)
                assert sock.recv(4096) == b""
            finally:
                sock.close()
        finally:
            gw.stop_sync()


class TestRequestTracing:
    TRACE = "0af7651916cd43dd8448eb211c80319c"

    def test_spans_correlated_across_threads_and_echoed(self, tiny_llama):
        """One request's spans appear on BOTH the gateway (asyncio)
        thread and the engine worker thread, correlated by the client's
        trace id; the response echoes a traceparent and the terminal
        payload carries the trace id."""
        from scaletorch_tpu.telemetry.spans import SpanTracer

        tracer = SpanTracer(path=None, role="serve")  # memory-only tail
        engine = make_engine(tiny_llama, tracer=tracer)
        gw = ServingGateway(engine, port=0,
                            tracer=tracer).start_in_thread()
        try:
            status, headers, raw = post(
                gw.port,
                {"prompt": [1, 2, 3], "max_new_tokens": 4, "stream": True},
                headers=[("traceparent",
                          f"00-{self.TRACE}-b7ad6b7169203331-01")])
            assert status == 200
            assert headers.get("traceparent", "").startswith(
                f"00-{self.TRACE}-")
            dones = [d for e, d in parse_sse_stream(raw) if e == "done"]
            assert dones[0]["trace_id"] == self.TRACE

            # a MALFORMED traceparent degrades to a fresh trace — the
            # request still succeeds and gets a well-formed id
            status, headers2, raw2 = post(
                gw.port,
                {"prompt": [4, 5], "max_new_tokens": 2, "stream": False},
                headers=[("traceparent", "garbage-in")])
            assert status == 200
            fresh = json.loads(raw2)["trace_id"]
            assert len(fresh) == 32 and fresh != self.TRACE
            assert headers2.get("traceparent", "").startswith(f"00-{fresh}")
        finally:
            gw.stop_sync()
        ours = [e for e in tracer.tail() if e.get("id") == self.TRACE]
        names = {e["name"] for e in ours}
        assert {"gw.request", "gw.queued", "gw.stream"} <= names, names
        assert {"request", "req.queued", "req.prefill", "req.decode",
                "req.finalize"} <= names, names
        gw_tids = {e["tid"] for e in ours if e["name"].startswith("gw.")}
        eng_tids = {e["tid"] for e in ours if e["name"].startswith("req.")}
        assert gw_tids and eng_tids and not (gw_tids & eng_tids), (
            gw_tids, eng_tids)
        finalize = [e for e in ours if e["name"] == "req.finalize"]
        assert finalize[0]["args"]["outcome"] == "ok"

    def test_untraced_gateway_works_without_tracer(self, tiny_llama):
        """No tracer attached: the request still gets a trace id (for
        the access log) and everything else behaves identically."""
        gw = ServingGateway(make_engine(tiny_llama),
                            port=0).start_in_thread()
        try:
            status, _, raw = post(
                gw.port, {"prompt": [1], "max_new_tokens": 2,
                          "stream": False})
            assert status == 200
            assert len(json.loads(raw)["trace_id"]) == 32
        finally:
            gw.stop_sync()


class TestSLOHealthz:
    def test_healthz_carries_live_slo_verdict(self, tiny_llama):
        targets = {"min_requests": 1, "error_budget": 0.5,
                   "targets": {"ttft_p95_s": 300.0, "e2e_p99_s": 300.0}}
        gw = ServingGateway(make_engine(tiny_llama), port=0,
                            slo_targets=targets).start_in_thread()
        try:
            status, raw = get(gw.port, "/healthz")
            slo = json.loads(raw)["slo"]
            assert slo["ok"] is True and slo.get("insufficient_data")
            post(gw.port, {"prompt": [1, 2], "max_new_tokens": 2,
                           "stream": False})
            status, raw = get(gw.port, "/healthz")
            assert status == 200
            slo = json.loads(raw)["slo"]
            assert slo["ok"] is True and slo["requests"] == 1
            assert {c["name"] for c in slo["checks"]} == {
                "error_budget", "ttft_p95_s", "e2e_p99_s"}
        finally:
            gw.stop_sync()

    def test_refusals_do_not_feed_latency_histograms(self, tiny_llama):
        """A 400/shed terminal takes microseconds — it must count as an
        outcome but never as a latency observation, or overload would
        drag the SLO quantiles DOWN (confirmed-bug regression)."""
        gw = ServingGateway(make_engine(tiny_llama),
                            port=0).start_in_thread()
        try:
            post(gw.port, {"prompt": []})  # 400 rejected
            post(gw.port, {"prompt": [1, 2], "max_new_tokens": 2,
                           "stream": False})
        finally:
            gw.stop_sync()
        assert gw.metrics.outcomes["rejected"] == 1
        assert gw.metrics.outcomes["ok"] == 1
        assert gw.hists.merged("e2e").count == 1  # the served request only

    def test_healthz_slo_violation_reported_not_fatal(self, tiny_llama):
        """An SLO violation is a VERDICT on /healthz, not an outage:
        the endpoint stays 200 (liveness and latency budgets are
        different alarms)."""
        targets = {"min_requests": 1, "error_budget": 1.0,
                   "targets": {"ttft_p95_s": 1e-9}}
        gw = ServingGateway(make_engine(tiny_llama), port=0,
                            slo_targets=targets).start_in_thread()
        try:
            post(gw.port, {"prompt": [1, 2], "max_new_tokens": 2,
                           "stream": False})
            status, raw = get(gw.port, "/healthz")
            assert status == 200
            slo = json.loads(raw)["slo"]
            assert slo["ok"] is False
            assert slo["violations"] == ["ttft_p95_s"]
        finally:
            gw.stop_sync()


class TestServeLiveSnapshotter:
    def test_snapshot_fn_payload_shape(self, tiny_llama, tmp_path):
        """scripts/serve.py's SIGUSR1 snapshot payload: span tail +
        gateway gauges + per-tenant histograms + per-replica engine
        snapshots/histograms (the handler itself is PR 8 machinery,
        already signal-tested in tests/test_telemetry.py)."""
        import os
        import sys

        repo = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        sys.path.insert(0, os.path.join(repo, "scripts"))
        import serve as serve_mod

        from scaletorch_tpu.telemetry.spans import SpanTracer

        tracer = SpanTracer(path=None, role="serve")
        gw = ServingGateway(make_engine(tiny_llama, tracer=tracer),
                            port=0, tracer=tracer).start_in_thread()
        try:
            post(gw.port, {"prompt": [1, 2], "max_new_tokens": 2,
                           "stream": False})
            args = serve_mod.parse_args(
                ["--telemetry_dir", str(tmp_path)])
            snapshotter = serve_mod.make_snapshotter(args, gw)
            payload = snapshotter.snapshot_fn()
            assert payload["gateway"]["http_requests_received"] == 1
            assert payload["tenant_histograms"]["e2e"]["default"][
                "count"] == 1
            replica = payload["replicas"]["r0"]
            assert replica["alive"] is True
            assert replica["histograms"]["ttft"]["count"] == 1
            assert payload["span_timeline_tail"]
            assert payload["slo"] is None
        finally:
            gw.stop_sync()


def sse_disconnect_after_first_token(port, body):
    """Raw-socket SSE client that walks away mid-stream."""
    payload = json.dumps(body).encode()
    sock = socket.create_connection(("127.0.0.1", port), timeout=30)
    try:
        sock.sendall(
            b"POST /v1/generate HTTP/1.1\r\n"
            b"Host: x\r\nContent-Type: application/json\r\n"
            + f"Content-Length: {len(payload)}\r\n\r\n".encode() + payload)
        got = b""
        while b"event: token" not in got:
            chunk = sock.recv(4096)
            if not chunk:
                raise AssertionError(f"stream closed early: {got!r}")
            got += chunk
    finally:
        sock.close()  # mid-stream disconnect


class TestDisconnectReleasesPages:
    def test_mid_stream_disconnect_aborts_and_releases(self, tiny_llama):
        engine = make_engine(tiny_llama, max_slots=1)
        gw = ServingGateway(engine, port=0).start_in_thread()
        try:
            sse_disconnect_after_first_token(
                gw.port, {"prompt": [1, 2, 3, 4, 5],
                          "max_new_tokens": 25, "stream": True})
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                if gw.metrics.outcomes["aborted"] == 1 \
                        and engine.metrics.outcomes["aborted"] == 1:
                    break
                time.sleep(0.02)
            assert gw.metrics.outcomes["aborted"] == 1
            assert engine.metrics.outcomes["aborted"] == 1
            # pages released: only radix-pinned prefix pages may remain,
            # and the allocator's books must balance exactly
            engine.allocator.check_conservation()
            for page, count in list(engine.allocator._ref.items()):
                assert count == 1, \
                    f"page {page} still slot-referenced after abort"
            # the freed slot keeps serving
            status, _, raw = post(
                gw.port, {"prompt": [9, 9], "max_new_tokens": 2,
                          "stream": False})
            assert status == 200
        finally:
            gw.stop_sync()
        gw.metrics.check_conservation()


class TestWorkerEdges:
    def test_submit_to_dead_worker_still_answers(self, tiny_llama):
        """The dispatcher's health check and the submit are not atomic:
        a closure enqueued into a dead worker's inbox must still be
        answered (rejected), never stranded."""
        from scaletorch_tpu.serving.gateway import EngineWorker

        worker = EngineWorker(make_engine(tiny_llama), replica_id="rX")
        worker.start()
        worker.shutdown(drain=True)
        worker.join(timeout=60)
        assert not worker.alive and worker.exit_code == 0
        done = []
        from scaletorch_tpu.serving.protocol import GenerateRequest

        worker.submit(GenerateRequest(prompt=[1, 2]),
                      lambda rid, toks: None,
                      lambda result: done.append(result))
        assert len(done) == 1
        assert done[0].outcome == "rejected"

    def test_instant_disconnect_keeps_conservation(self, tiny_llama):
        """A client that closes its socket without reading ANY response
        bytes (before the SSE headers flush) must still leave exactly
        one recorded outcome — the write-failure path takes the same
        cancel/abort route as a mid-stream disconnect."""
        engine = make_engine(tiny_llama)
        gw = ServingGateway(engine, port=0).start_in_thread()
        try:
            payload = json.dumps({"prompt": [1, 2, 3],
                                  "max_new_tokens": 20,
                                  "stream": True}).encode()
            sock = socket.create_connection(("127.0.0.1", gw.port),
                                            timeout=30)
            sock.sendall(
                b"POST /v1/generate HTTP/1.1\r\nHost: x\r\n"
                + f"Content-Length: {len(payload)}\r\n\r\n".encode()
                + payload)
            sock.close()  # walk away before reading a single byte
            deadline = time.monotonic() + 30
            while (sum(gw.metrics.outcomes.values()) < 1
                   and time.monotonic() < deadline):
                time.sleep(0.02)
            # a later request still works and the ledger balances
            status, _, _ = post(gw.port, {"prompt": [5], "stream": False,
                                          "max_new_tokens": 2})
            assert status == 200
        finally:
            gw.stop_sync()
        gw.metrics.check_conservation()
        engine.allocator.check_conservation()


class TestConservationProperty:
    """Acceptance: every accepted connection receives exactly one
    terminal status, and http_requests_received == sum(outcomes) across
    randomized storm/deadline/disconnect schedules."""

    @pytest.mark.parametrize("seed", [0, 1])
    def test_storm_deadline_disconnect_schedules(self, tiny_llama, seed):
        import random

        rng = random.Random(seed)
        engine = make_engine(tiny_llama, max_slots=2)
        gw = ServingGateway(
            engine, port=0, max_backlog=3,
            tenants={"flood": TenantConfig("flood", weight=1.0)},
        ).start_in_thread()
        statuses = []
        lock = threading.Lock()

        def one_request(i):
            kind = rng.random()
            tenant = rng.choice(["flood", "quiet", "default"])
            body = {"prompt": [1 + i % 8, 2, 3],
                    "max_new_tokens": rng.randint(1, 6),
                    "tenant": tenant}
            if kind < 0.2:
                body["ttl_s"] = 0.001  # near-certain timeout
            if kind >= 0.2 and kind < 0.35:
                try:
                    sse_disconnect_after_first_token(
                        gw.port, dict(body, stream=True,
                                      max_new_tokens=20))
                except (AssertionError, OSError):
                    pass
                return  # disconnects are recorded gateway-side
            body["stream"] = rng.random() < 0.5
            status, headers, raw = post(gw.port, body, timeout=120)
            if body["stream"] and status == 200:
                events = parse_sse_stream(raw)
                dones = [d for e, d in events if e == "done"]
                assert len(dones) == 1, "exactly one terminal per stream"
                status = STATUS_BY_OUTCOME[dones[0]["outcome"]]
            elif status == 429:
                assert "Retry-After" in headers
            with lock:
                statuses.append(status)

        try:
            threads = [threading.Thread(target=one_request, args=(i,))
                       for i in range(24)]
            # staggered storm: bursts + breathers
            for i, thread in enumerate(threads):
                thread.start()
                if rng.random() < 0.3:
                    time.sleep(0.03)
            for thread in threads:
                thread.join(timeout=180)
                assert not thread.is_alive(), "request hung"
        finally:
            gw.stop_sync()
        # every terminal status is one of the contract's statuses
        allowed = set(STATUS_BY_OUTCOME.values()) | {400}
        assert all(s in allowed for s in statuses), statuses
        gw.metrics.check_conservation()
        total = sum(gw.metrics.outcomes.values())
        assert total == gw.metrics.http_requests_received
        # the engine's own conservation held underneath
        engine_outcomes = sum(engine.metrics.outcomes.values())
        assert engine_outcomes == engine.metrics.requests_submitted
        engine.allocator.check_conservation()


class TestTenantFairnessE2E:
    def test_victim_tenant_served_within_weight_share(self, tiny_llama):
        """One tenant floods 8 requests ahead of the victim's 2; with
        equal weights the victim's requests complete well before the
        flood drains (FIFO would finish the entire flood first)."""
        engine = make_engine(tiny_llama, max_slots=1)
        gw = ServingGateway(engine, port=0).start_in_thread()
        order = []
        lock = threading.Lock()

        def run_one(tenant, i, n_tokens):
            status, _, _ = post(
                gw.port, {"prompt": [3, 1 + i],
                          "max_new_tokens": n_tokens,
                          "tenant": tenant, "stream": False}, timeout=300)
            with lock:
                order.append((tenant, status))

        try:
            # an occupier pins the single slot (and pays the first
            # compile) so every later arrival genuinely QUEUES — the
            # fairness decision happens in the gateway's WFQ, not in a
            # race against the engine draining the flood first
            occupier = threading.Thread(
                target=run_one, args=("flood", 0, 25))
            occupier.start()
            time.sleep(0.2)
            floods = [threading.Thread(target=run_one,
                                       args=("flood", i, 6))
                      for i in range(1, 8)]
            for thread in floods:
                thread.start()
            time.sleep(0.2)  # the flood queues first; victims arrive last
            victims = [threading.Thread(target=run_one,
                                        args=("victim", i, 6))
                       for i in range(2)]
            for thread in victims:
                thread.start()
            for thread in [occupier] + floods + victims:
                thread.join(timeout=300)
        finally:
            gw.stop_sync()
        assert all(status == 200 for _, status in order), order
        positions = [i for i, (tenant, _) in enumerate(order)
                     if tenant == "victim"]
        assert len(positions) == 2
        # WFQ interleaves the victim within its equal-weight share of
        # the remaining service; a FIFO gateway would park both victims
        # at positions 8 and 9 (after the entire flood)
        assert max(positions) <= 6, (positions, order)
        gw.metrics.check_conservation()


class TestGatewayDrills:
    def test_tenant_storm_drill(self, tiny_llama):
        injector = ServingFaultInjector(
            gw_tenant_storm_at=1, gw_tenant_storm_count=6)
        engine = make_engine(tiny_llama, max_slots=2)
        gw = ServingGateway(
            engine, port=0, injector=injector, max_backlog=4,
        ).start_in_thread()
        try:
            # arrival 1 triggers the storm; victim requests still finish
            for i in range(3):
                status, _, raw = post(
                    gw.port, {"prompt": [2 + i, 3], "max_new_tokens": 2,
                              "tenant": "victim", "stream": False},
                    timeout=120)
                assert status == 200, raw
        finally:
            gw.stop_sync()
        assert gw.metrics.injected_storm_requests == 6
        storm_total = sum(gw.metrics.storm_outcomes.values())
        assert storm_total == 6  # every storm request reached a terminal
        assert gw.metrics.storm_outcomes["shed"] > 0  # backlog cap bit
        gw.metrics.check_conservation()  # HTTP ledger unpolluted

    def test_replica_down_drill(self, tiny_llama):
        injector = ServingFaultInjector(gw_replica_down_at=1)
        engines = {"r0": make_engine(tiny_llama),
                   "r1": make_engine(tiny_llama)}
        gw = ServingGateway(
            engines, port=0, injector=injector).start_in_thread()
        try:
            status, _, raw = post(
                gw.port, {"prompt": [1, 2, 3], "max_new_tokens": 10,
                          "stream": True}, timeout=120)
            assert status == 200
            events = parse_sse_stream(raw)
            dones = [d for e, d in events if e == "done"]
            assert len(dones) == 1
            assert dones[0]["outcome"] == "aborted"  # died mid-stream
            # the survivor keeps serving; routing avoids the corpse
            for i in range(3):
                status, _, raw = post(
                    gw.port, {"prompt": [7 + i, 8], "max_new_tokens": 2,
                              "stream": False}, timeout=120)
                assert status == 200, raw
            snap = gw.router.snapshot()
            assert snap["router_replicas_dead"] == 1.0
            assert snap["router_replicas_alive"] == 1.0
            dead = [rid for rid, st in gw.router.replicas.items()
                    if not st.healthy][0]
            assert gw.workers[dead].exit_code == 44
            status, raw = get(gw.port, "/healthz")
            assert status == 200  # one survivor = still healthy
            assert json.loads(raw)["replicas"][dead]["alive"] is False
        finally:
            gw.stop_sync()
        gw.metrics.check_conservation()

    def test_injector_config_env_parity(self, monkeypatch):
        class Cfg:
            ft_gw_tenant_storm_at = 5
            ft_gw_tenant_storm_count = 9
            ft_gw_replica_down_at = 3

        inj = ServingFaultInjector.from_config(Cfg())
        assert inj.gw_tenant_storm_at == 5
        assert inj.gw_tenant_storm_count == 9
        assert inj.gw_replica_down_at == 3
        assert inj.active
        # present env wins over config
        monkeypatch.setenv("SCALETORCH_TPU_FT_GW_TENANT_STORM_AT", "2")
        inj = ServingFaultInjector.from_config(Cfg())
        assert inj.gw_tenant_storm_at == 2
        # explicit 0 CANCELS a config-armed drill (the restart contract)
        monkeypatch.setenv("SCALETORCH_TPU_FT_GW_TENANT_STORM_AT", "0")
        monkeypatch.setenv("SCALETORCH_TPU_FT_GW_REPLICA_DOWN_AT", "0")
        inj = ServingFaultInjector.from_config(Cfg())
        assert inj.gw_tenant_storm_at == 0
        assert inj.gw_replica_down_at == 0
        assert not inj.active

    def test_fires_once_at_exact_arrival(self):
        inj = ServingFaultInjector(gw_tenant_storm_at=3,
                                   gw_tenant_storm_count=4)
        assert inj.take_gw_tenant_storm(1) == 0
        assert inj.take_gw_tenant_storm(2) == 0
        assert inj.take_gw_tenant_storm(3) == 4
        assert inj.take_gw_tenant_storm(3) == 0  # fires once
        inj2 = ServingFaultInjector(gw_replica_down_at=2)
        assert not inj2.take_gw_replica_down(1)
        assert inj2.take_gw_replica_down(2)
        assert not inj2.take_gw_replica_down(2)


class TestDrain:
    def test_stop_drains_in_flight_and_aborts_queued(self, tiny_llama):
        engine = make_engine(tiny_llama, max_slots=1)
        gw = ServingGateway(engine, port=0).start_in_thread()
        results = {}
        lock = threading.Lock()

        def run_one(name, n_tokens):
            status, _, raw = post(
                gw.port, {"prompt": [1, 2], "max_new_tokens": n_tokens,
                          "stream": False}, timeout=120)
            with lock:
                results[name] = (status, json.loads(raw))

        in_flight = threading.Thread(target=run_one, args=("active", 20))
        queued = threading.Thread(target=run_one, args=("queued", 20))
        in_flight.start()
        time.sleep(0.5)  # let it dispatch and start decoding
        queued.start()
        deadline = time.monotonic() + 30
        while (gw.metrics.http_requests_received < 2
               and time.monotonic() < deadline):
            time.sleep(0.02)  # both requests must be IN before the drain
        gw.stop_sync(drain=True)
        in_flight.join(timeout=60)
        queued.join(timeout=60)
        assert results["active"][0] == 200
        assert results["active"][1]["outcome"] == "ok"
        assert len(results["active"][1]["token_ids"]) == 20
        assert results["queued"][1]["outcome"] in ("aborted", "ok")
        # post-drain: the worker exited cleanly, pools balance
        assert gw.workers["r0"].exit_code == 0
        engine.allocator.check_conservation()
        gw.metrics.check_conservation()
        # a post-drain arrival is refused, not hung
        status, _, raw = None, None, None
        try:
            status, _, raw = post(
                gw.port, {"prompt": [1], "max_new_tokens": 1}, timeout=5)
        except (urllib.error.URLError, OSError):
            pass  # socket closed entirely — equally correct
        if status is not None:
            assert status in (503, 429)
