"""Gateway over the disaggregated engine (ISSUE 19): real HTTP SSE
bit-parity against the colocated oracle, HTTP-ledger conservation, the
per-slice /healthz block and the handoff metric families on /metrics.

Quick tier, CPU (8 virtual devices via conftest). Same harness idiom as
test_gateway.py: a real ``ServingGateway`` on an ephemeral port, urllib
clients, the colocated paged engine as the arithmetic oracle.
"""

import json
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from scaletorch_tpu.inference import (
    DisaggregatedEngine,
    InferenceEngine,
    SamplingParams,
)
from scaletorch_tpu.models import llama
from scaletorch_tpu.serving.gateway import ServingGateway
from scaletorch_tpu.serving.protocol import parse_sse_stream, stream_tokens

TINY = dict(
    vocab_size=64, hidden_size=32, intermediate_size=64,
    num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
    dtype=jnp.float32,
)
PAGE = 4


@pytest.fixture(scope="module")
def tiny_llama():
    cfg = llama.LlamaConfig(**TINY)
    return cfg, llama.init_params(jax.random.PRNGKey(0), cfg)


def engine_kw(**kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("max_seq", 32)
    kw.setdefault("prefill_len", 16)
    kw.setdefault("sampling", SamplingParams(temperature=0.0))
    kw.setdefault("page_size", PAGE)
    kw.setdefault("strict_submit", False)
    return kw


def make_disagg(tiny_llama, **kw):
    cfg, params = tiny_llama
    return DisaggregatedEngine(
        params, cfg, disagg_split=(4, 4), **engine_kw(**kw))


def ref_tokens(tiny_llama, prompt, n):
    """COLOCATED direct-engine oracle — parity is asserted across the
    architecture split, not disagg-vs-itself."""
    cfg, params = tiny_llama
    eng = InferenceEngine(
        params, cfg, cache_layout="paged", **engine_kw())
    rid = eng.submit(prompt, max_new_tokens=n)
    return eng.run()[rid].tokens


def post(port, body, *, timeout=60):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/generate",
        data=json.dumps(body).encode(), method="POST")
    try:
        resp = urllib.request.urlopen(req, timeout=timeout)
        return resp.status, resp.read()
    except urllib.error.HTTPError as err:
        return err.code, err.read()


def get(port, path, timeout=30):
    resp = urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=timeout)
    return resp.status, resp.read()


class TestDisaggGateway:
    def test_sse_parity_healthz_and_metrics(self, tiny_llama):
        """One gateway boot covers the e2e acceptance: streamed tokens
        bit-identical to the colocated engine, exactly one terminal per
        request (HTTP conservation), the disagg block live on /healthz,
        the per-slice gauges + handoff_seconds histogram on /metrics,
        and one compile per slice program."""
        engine = make_disagg(tiny_llama)
        gw = ServingGateway(engine, port=0).start_in_thread()
        try:
            prompts = [[1, 2, 3], [7, 8, 9, 10], [4, 4, 4]]
            for prompt in prompts:
                status, raw = post(
                    gw.port,
                    {"prompt": prompt, "max_new_tokens": 6,
                     "stream": True})
                assert status == 200
                events = parse_sse_stream(raw)
                dones = [d for e, d in events if e == "done"]
                assert len(dones) == 1, events
                assert dones[0]["outcome"] == "ok"
                streamed = stream_tokens(events)
                assert streamed == dones[0]["token_ids"]
                assert streamed == ref_tokens(tiny_llama, prompt, 6)
            assert engine.prefill_compile_count == 1
            assert engine.decode_compile_count == 1

            _, raw = get(gw.port, "/healthz")
            health = json.loads(raw)
            dis = health["replicas"]["r0"]["disagg"]
            assert dis["prefill_slice"]["devices"] == 4
            assert dis["decode_slice"]["devices"] == 4
            assert dis["handoffs"] == len(prompts)
            assert dis["handoff_failures"] == 0
            assert dis["pages_handed_off"] >= len(prompts)
            assert dis["prefill_slice"]["pages_in_use"] == 0  # drained
            assert 0.0 <= dis["prefill_slice"]["busy_fraction"] <= 1.0
            assert 0.0 <= dis["decode_slice"]["busy_fraction"] <= 1.0

            _, raw = get(gw.port, "/metrics")
            metrics = raw.decode()
            for needle in (
                'scaletorch_engine_prefill_slice_busy_fraction'
                '{replica="r0"}',
                'scaletorch_engine_decode_slice_busy_fraction'
                '{replica="r0"}',
                'scaletorch_engine_pages_handed_off{replica="r0"}',
                'scaletorch_engine_handoffs{replica="r0"} 3.0',
                'scaletorch_engine_handoff_failures{replica="r0"} 0.0',
                "# TYPE scaletorch_handoff_seconds histogram",
                'scaletorch_handoff_seconds_count{replica="r0"} 3',
            ):
                assert needle in metrics, f"missing {needle}"
        finally:
            gw.stop_sync()
        gw.metrics.check_conservation()
        engine.check_conservation()

    def test_colocated_healthz_has_no_disagg_block(self, tiny_llama):
        cfg, params = tiny_llama
        engine = InferenceEngine(
            params, cfg, cache_layout="paged", **engine_kw())
        gw = ServingGateway(engine, port=0).start_in_thread()
        try:
            _, raw = get(gw.port, "/healthz")
            health = json.loads(raw)
            assert "disagg" not in health["replicas"]["r0"]
            _, raw = get(gw.port, "/metrics")
            assert "scaletorch_handoff_seconds" not in raw.decode()
        finally:
            gw.stop_sync()
