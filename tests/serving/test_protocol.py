"""Wire-schema tests: request validation, SSE framing, outcome mapping.

Pure host-side (no engine, no HTTP) — the protocol module is stdlib by
design and these run in milliseconds.
"""

import json

import pytest

from scaletorch_tpu.inference.resilience import TERMINAL_OUTCOMES
from scaletorch_tpu.serving import protocol
from scaletorch_tpu.serving.protocol import (
    PROTOCOL_VERSION,
    STATUS_BY_OUTCOME,
    ProtocolError,
    format_sse_event,
    parse_generate_request,
    parse_sse_stream,
    stream_tokens,
)


class TestRequestParsing:
    def test_minimal_request(self):
        req = parse_generate_request(b'{"prompt": [1, 2, 3]}')
        assert req.prompt == [1, 2, 3]
        assert req.max_new_tokens == 64
        assert req.stream is True
        assert req.tenant == "default"
        assert req.ttl_s is None
        assert req.cost == 3 + 64

    def test_full_request(self):
        body = json.dumps({
            "prompt": [5], "max_new_tokens": 8, "eos_id": 2, "seed": 9,
            "ttl_s": 1.5, "tenant": "pro", "stream": False,
            "x_custom": "kept",
        }).encode()
        req = parse_generate_request(body)
        assert (req.max_new_tokens, req.eos_id, req.seed) == (8, 2, 9)
        assert req.ttl_s == 1.5
        assert req.tenant == "pro"
        assert req.stream is False
        assert req.extra == {"x_custom": "kept"}

    def test_header_tenant_fallback_body_wins(self):
        req = parse_generate_request(
            b'{"prompt": [1]}', header_tenant="hdr")
        assert req.tenant == "hdr"
        req = parse_generate_request(
            b'{"prompt": [1], "tenant": "body"}', header_tenant="hdr")
        assert req.tenant == "body"

    @pytest.mark.parametrize("body, match", [
        (b"not json", "valid JSON"),
        (b"[1,2]", "JSON object"),
        (b"{}", "prompt"),
        (b'{"prompt": []}', "prompt"),
        (b'{"prompt": [1.5]}', "prompt"),
        (b'{"prompt": [true]}', "prompt"),
        (b'{"prompt": "text"}', "prompt"),
        (b'{"prompt": [1], "max_new_tokens": 0}', "max_new_tokens"),
        (b'{"prompt": [1], "max_new_tokens": "8"}', "max_new_tokens"),
        (b'{"prompt": [1], "seed": -1}', "seed"),
        (b'{"prompt": [1], "eos_id": "x"}', "eos_id"),
        (b'{"prompt": [1], "ttl_s": 0}', "ttl_s"),
        (b'{"prompt": [1], "ttl_s": -2}', "ttl_s"),
        (b'{"prompt": [1], "tenant": ""}', "tenant"),
        (b'{"prompt": [1], "stream": 1}', "stream"),
    ])
    def test_rejects_malformed(self, body, match):
        with pytest.raises(ProtocolError, match=match):
            parse_generate_request(body)


class TestOutcomeMapping:
    def test_every_outcome_has_exactly_one_status(self):
        assert set(STATUS_BY_OUTCOME) == set(TERMINAL_OUTCOMES)
        assert STATUS_BY_OUTCOME["ok"] == 200
        assert STATUS_BY_OUTCOME["shed"] == 429
        assert STATUS_BY_OUTCOME["timeout"] == 504
        assert STATUS_BY_OUTCOME["rejected"] == 503

    def test_payloads_carry_version(self):
        done = protocol.result_payload(
            3, outcome="ok", finish_reason="length", token_ids=[1, 2],
            prompt_tokens=4)
        assert done["v"] == PROTOCOL_VERSION
        assert done["usage"] == {"prompt_tokens": 4,
                                 "completion_tokens": 2}
        assert protocol.token_payload(3, [7])["v"] == PROTOCOL_VERSION
        err = protocol.error_payload("too busy", outcome="shed",
                                     retry_after_s=2.0)
        assert err["v"] == PROTOCOL_VERSION
        assert err["retry_after_s"] == 2.0


class TestTraceparent:
    """W3C trace-context parsing: valid headers round-trip, EVERYTHING
    else degrades to None (fresh trace) — never an exception, never a
    500 (the gateway handler relies on it)."""

    TRACE = "0af7651916cd43dd8448eb211c80319c"
    SPAN = "b7ad6b7169203331"

    def test_valid_round_trip(self):
        header = protocol.make_traceparent(self.TRACE, self.SPAN)
        assert header == f"00-{self.TRACE}-{self.SPAN}-01"
        assert protocol.parse_traceparent(header) == (self.TRACE, self.SPAN)
        unsampled = protocol.make_traceparent(
            self.TRACE, self.SPAN, sampled=False)
        assert protocol.parse_traceparent(unsampled) == (self.TRACE,
                                                         self.SPAN)

    def test_surrounding_whitespace_ok(self):
        header = f"  00-{self.TRACE}-{self.SPAN}-01  "
        assert protocol.parse_traceparent(header) == (self.TRACE, self.SPAN)

    def test_future_version_with_extra_fields_accepted(self):
        header = f"cc-{self.TRACE}-{self.SPAN}-01-extra-stuff"
        assert protocol.parse_traceparent(header) == (self.TRACE, self.SPAN)

    @pytest.mark.parametrize("header", [
        None,
        "",
        "00",
        f"00-{TRACE}-{SPAN}",                      # missing flags
        f"00-{TRACE}-{SPAN}-01-extra",             # v00 forbids extras
        f"ff-{TRACE}-{SPAN}-01",                   # version ff invalid
        f"00-{'0' * 32}-{SPAN}-01",                # all-zero trace id
        f"00-{TRACE}-{'0' * 16}-01",               # all-zero span id
        f"00-{TRACE.upper()}-{SPAN}-01",           # uppercase hex
        f"00-{TRACE[:-1]}-{SPAN}-01",              # short trace id
        f"00-{TRACE}-{SPAN}x-01",                  # long span id
        f"00-{TRACE}-{SPAN}-0g",                   # non-hex flags
        "00_" + TRACE,                             # wrong separators
        "\x00\xff garbage \n",
        "00-" + "zz" * 16 + f"-{SPAN}-01",
    ])
    def test_malformed_degrades_to_none(self, header):
        assert protocol.parse_traceparent(header) is None

    def test_malformed_fuzz_never_raises(self):
        import random
        import string

        rng = random.Random(0)
        alphabet = string.printable + "\x00\xff"
        for _ in range(500):
            header = "".join(rng.choice(alphabet)
                             for _ in range(rng.randint(0, 80)))
            result = protocol.parse_traceparent(header)
            assert result is None or (
                len(result[0]) == 32 and len(result[1]) == 16)

    def test_fresh_ids_wellformed_and_distinct(self):
        tid, sid = protocol.new_trace_id(), protocol.new_span_id()
        assert len(tid) == 32 and int(tid, 16) != 0
        assert len(sid) == 16 and int(sid, 16) != 0
        assert protocol.new_trace_id() != tid
        # a minted id parses back through its own header form
        assert protocol.parse_traceparent(
            protocol.make_traceparent(tid, sid)) == (tid, sid)

    def test_result_payload_carries_trace_id(self):
        done = protocol.result_payload(
            1, outcome="ok", finish_reason="length", token_ids=[1],
            prompt_tokens=1, trace_id=self.TRACE)
        assert done["trace_id"] == self.TRACE


class TestSSEFraming:
    def test_round_trip(self):
        raw = b"".join([
            format_sse_event("token", protocol.token_payload(1, [4])),
            format_sse_event("token", protocol.token_payload(1, [5, 6])),
            format_sse_event("done", protocol.result_payload(
                1, outcome="ok", finish_reason="length",
                token_ids=[4, 5, 6], prompt_tokens=2)),
        ])
        events = parse_sse_stream(raw)
        assert [name for name, _ in events] == ["token", "token", "done"]
        assert stream_tokens(events) == [4, 5, 6]
        assert events[-1][1]["token_ids"] == [4, 5, 6]

    def test_partial_noise_tolerated(self):
        raw = (b": comment\n\n"
               + format_sse_event("token", protocol.token_payload(0, [9])))
        events = parse_sse_stream(raw)
        assert stream_tokens(events) == [9]
