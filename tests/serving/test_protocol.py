"""Wire-schema tests: request validation, SSE framing, outcome mapping.

Pure host-side (no engine, no HTTP) — the protocol module is stdlib by
design and these run in milliseconds.
"""

import json

import pytest

from scaletorch_tpu.inference.resilience import TERMINAL_OUTCOMES
from scaletorch_tpu.serving import protocol
from scaletorch_tpu.serving.protocol import (
    PROTOCOL_VERSION,
    STATUS_BY_OUTCOME,
    ProtocolError,
    format_sse_event,
    parse_generate_request,
    parse_sse_stream,
    stream_tokens,
)


class TestRequestParsing:
    def test_minimal_request(self):
        req = parse_generate_request(b'{"prompt": [1, 2, 3]}')
        assert req.prompt == [1, 2, 3]
        assert req.max_new_tokens == 64
        assert req.stream is True
        assert req.tenant == "default"
        assert req.ttl_s is None
        assert req.cost == 3 + 64

    def test_full_request(self):
        body = json.dumps({
            "prompt": [5], "max_new_tokens": 8, "eos_id": 2, "seed": 9,
            "ttl_s": 1.5, "tenant": "pro", "stream": False,
            "x_custom": "kept",
        }).encode()
        req = parse_generate_request(body)
        assert (req.max_new_tokens, req.eos_id, req.seed) == (8, 2, 9)
        assert req.ttl_s == 1.5
        assert req.tenant == "pro"
        assert req.stream is False
        assert req.extra == {"x_custom": "kept"}

    def test_header_tenant_fallback_body_wins(self):
        req = parse_generate_request(
            b'{"prompt": [1]}', header_tenant="hdr")
        assert req.tenant == "hdr"
        req = parse_generate_request(
            b'{"prompt": [1], "tenant": "body"}', header_tenant="hdr")
        assert req.tenant == "body"

    @pytest.mark.parametrize("body, match", [
        (b"not json", "valid JSON"),
        (b"[1,2]", "JSON object"),
        (b"{}", "prompt"),
        (b'{"prompt": []}', "prompt"),
        (b'{"prompt": [1.5]}', "prompt"),
        (b'{"prompt": [true]}', "prompt"),
        (b'{"prompt": "text"}', "prompt"),
        (b'{"prompt": [1], "max_new_tokens": 0}', "max_new_tokens"),
        (b'{"prompt": [1], "max_new_tokens": "8"}', "max_new_tokens"),
        (b'{"prompt": [1], "seed": -1}', "seed"),
        (b'{"prompt": [1], "eos_id": "x"}', "eos_id"),
        (b'{"prompt": [1], "ttl_s": 0}', "ttl_s"),
        (b'{"prompt": [1], "ttl_s": -2}', "ttl_s"),
        (b'{"prompt": [1], "tenant": ""}', "tenant"),
        (b'{"prompt": [1], "stream": 1}', "stream"),
    ])
    def test_rejects_malformed(self, body, match):
        with pytest.raises(ProtocolError, match=match):
            parse_generate_request(body)


class TestOutcomeMapping:
    def test_every_outcome_has_exactly_one_status(self):
        assert set(STATUS_BY_OUTCOME) == set(TERMINAL_OUTCOMES)
        assert STATUS_BY_OUTCOME["ok"] == 200
        assert STATUS_BY_OUTCOME["shed"] == 429
        assert STATUS_BY_OUTCOME["timeout"] == 504
        assert STATUS_BY_OUTCOME["rejected"] == 503

    def test_payloads_carry_version(self):
        done = protocol.result_payload(
            3, outcome="ok", finish_reason="length", token_ids=[1, 2],
            prompt_tokens=4)
        assert done["v"] == PROTOCOL_VERSION
        assert done["usage"] == {"prompt_tokens": 4,
                                 "completion_tokens": 2}
        assert protocol.token_payload(3, [7])["v"] == PROTOCOL_VERSION
        err = protocol.error_payload("too busy", outcome="shed",
                                     retry_after_s=2.0)
        assert err["v"] == PROTOCOL_VERSION
        assert err["retry_after_s"] == 2.0


class TestSSEFraming:
    def test_round_trip(self):
        raw = b"".join([
            format_sse_event("token", protocol.token_payload(1, [4])),
            format_sse_event("token", protocol.token_payload(1, [5, 6])),
            format_sse_event("done", protocol.result_payload(
                1, outcome="ok", finish_reason="length",
                token_ids=[4, 5, 6], prompt_tokens=2)),
        ])
        events = parse_sse_stream(raw)
        assert [name for name, _ in events] == ["token", "token", "done"]
        assert stream_tokens(events) == [4, 5, 6]
        assert events[-1][1]["token_ids"] == [4, 5, 6]

    def test_partial_noise_tolerated(self):
        raw = (b": comment\n\n"
               + format_sse_event("token", protocol.token_payload(0, [9])))
        events = parse_sse_stream(raw)
        assert stream_tokens(events) == [9]
