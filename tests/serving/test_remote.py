"""The replica wire: RemoteEngineWorker <-> ReplicaServer.

Three rings, inside out: (1) the wire alone — an in-process
``ReplicaServer`` over the jax-free ``FakeEngineWorker`` double, the
``RemoteEngineWorker`` client talking real HTTP/SSE to it; (2) real
child processes (fake_replica.py) — kill -9 mid-stream must synthesize
exactly one ``aborted`` terminal and flip ``alive``; SIGTERM must drain
to exit 0; (3) the acceptance attestation — a real tiny-Llama engine
behind the wire produces BIT-IDENTICAL greedy tokens to the same engine
driven directly, with ``decode_compile_count == 1`` (the process
boundary adds zero retraces).
"""

import asyncio
import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from scaletorch_tpu.serving.protocol import parse_generate_request
from scaletorch_tpu.serving.remote import RemoteEngineWorker, ReplicaServer

from .fake_replica import FakeEngineWorker

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
FAKE_REPLICA = os.path.join(TESTS_DIR, "fake_replica.py")


def make_req(prompt, n, **kw):
    body = {"prompt": list(prompt), "max_new_tokens": n, "stream": True}
    body.update(kw)
    return parse_generate_request(json.dumps(body).encode())


class ServerThread:
    """An in-process ReplicaServer on its own event-loop thread."""

    def __init__(self, worker):
        self.worker = worker
        self.server = None
        self.port = None
        self._loop = None
        self._started = threading.Event()
        self._thread = threading.Thread(
            target=lambda: asyncio.run(self._main()),
            name="replica-server-test", daemon=True)

    async def _main(self):
        self._loop = asyncio.get_running_loop()
        self.server = ReplicaServer(self.worker, port=0)
        await self.server.start()
        self.port = self.server.port
        self._started.set()
        await self.server.wait_drain()
        deadline = time.monotonic() + 5.0
        while self.worker.inflight > 0 and time.monotonic() < deadline:
            await asyncio.sleep(0.01)
        await self.server.close()

    def start(self):
        self._thread.start()
        assert self._started.wait(10), "replica server never bound"
        return self

    def stop(self):
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self.server.request_drain)
        self._thread.join(10)


def run_request(remote, req, *, timeout=30):
    """Submit through the remote handle; block for the terminal."""
    done = threading.Event()
    out = {"tokens": [], "result": None, "submitted": None}

    remote.submit(
        req,
        lambda rid, toks: out["tokens"].extend(toks),
        lambda res: (out.__setitem__("result", res), done.set()),
        ttl_s=req.ttl_s,
        on_submitted=lambda rid: out.__setitem__("submitted", rid),
    )
    assert done.wait(timeout), "no terminal result"
    return out


def spawn_fake_child(*extra_args):
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(
        os.path.dirname(TESTS_DIR)) + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, FAKE_REPLICA, *extra_args],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=env)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            raise RuntimeError(
                f"fake replica died before READY rc={proc.poll()}")
        if line.startswith("READY port="):
            return proc, int(line.strip().split("=")[1])
    raise RuntimeError("fake replica never printed READY")


class TestWireInProcess:
    """Ring 1: the wire alone, no child processes, no jax engine."""

    def test_stream_roundtrip_and_payload(self):
        worker = FakeEngineWorker(token_delay_s=0.0)
        srv = ServerThread(worker).start()
        remote = RemoteEngineWorker(
            "127.0.0.1", srv.port, replica_id="r0").start()
        try:
            assert remote.alive
            assert remote.page_size == worker.page_size
            out = run_request(remote, make_req([3, 1, 4], 6))
            res = out["result"]
            assert res.outcome == "ok"
            assert res.finish_reason == "length"
            expect = worker.expected_tokens([3, 1, 4], 6)
            assert out["tokens"] == expect
            assert res.tokens == expect
            assert out["submitted"] == res.request_id
            # the terminal carries the engine's latency attribution
            assert res.queue_wait_s == 0.0
            assert res.prefill_s == 0.0
            assert res.prefix_hit is False
            assert remote.inflight == 0
        finally:
            remote.stop_polling()
            srv.stop()

    def test_stop_polling_joins_the_poller(self):
        """stop_polling must wait for the poller thread, not just flag
        it — a replaced worker's poller may not outlive its successor
        (the ST1101 finding that seeded the ownership tier)."""
        worker = FakeEngineWorker(token_delay_s=0.0)
        srv = ServerThread(worker).start()
        remote = RemoteEngineWorker(
            "127.0.0.1", srv.port, replica_id="r0").start()
        try:
            assert remote._poller.is_alive()
        finally:
            remote.stop_polling()
            srv.stop()
        assert not remote._poller.is_alive()
        # before start() the poller has no ident: stop must not raise
        fresh = RemoteEngineWorker("127.0.0.1", srv.port, replica_id="rx")
        fresh.stop_polling()
        assert not fresh._poller.is_alive()

    def test_trace_id_rides_the_hop(self):
        worker = FakeEngineWorker(token_delay_s=0.0)
        srv = ServerThread(worker).start()
        remote = RemoteEngineWorker(
            "127.0.0.1", srv.port, replica_id="r0").start()
        try:
            req = make_req([5, 5], 2)
            req.trace_id = "a" * 32
            res = run_request(remote, req)["result"]
            assert res.trace_id == "a" * 32
        finally:
            remote.stop_polling()
            srv.stop()

    def test_cancel_mid_stream_aborts(self):
        worker = FakeEngineWorker(token_delay_s=0.05)
        srv = ServerThread(worker).start()
        remote = RemoteEngineWorker(
            "127.0.0.1", srv.port, replica_id="r0").start()
        try:
            done = threading.Event()
            got = {}
            submitted = threading.Event()
            rid_box = {}

            def on_submitted(rid):
                rid_box["rid"] = rid
                submitted.set()

            remote.submit(
                make_req([9, 9], 200),
                lambda rid, toks: None,
                lambda res: (got.__setitem__("res", res), done.set()),
                on_submitted=on_submitted)
            assert submitted.wait(10)
            remote.cancel(rid_box["rid"], "test cancel")
            assert done.wait(10)
            assert got["res"].outcome == "aborted"
            assert got["res"].detail == "test cancel"
            assert remote.inflight == 0
        finally:
            remote.stop_polling()
            srv.stop()

    def test_gauges_polled_and_ticks_fire(self):
        worker = FakeEngineWorker(token_delay_s=0.0)
        srv = ServerThread(worker).start()
        remote = RemoteEngineWorker(
            "127.0.0.1", srv.port, replica_id="r0",
            poll_interval_s=0.02).start()
        try:
            ticks = []
            remote.tick_listeners.append(lambda: ticks.append(1))
            deadline = time.monotonic() + 5
            while not remote.gauges() and time.monotonic() < deadline:
                time.sleep(0.02)
            gauges = remote.gauges()
            assert gauges["page_pool_free"] == float(worker.page_pool)
            assert "slot_occupancy" in gauges
            assert ticks, "poller never fired tick listeners"
            assert remote.pid == os.getpid()  # in-process server
        finally:
            remote.stop_polling()
            srv.stop()

    def test_refused_submit_is_rejected_terminal(self):
        """A 4xx on /v1/submit still yields exactly one terminal."""
        worker = FakeEngineWorker(token_delay_s=0.0)
        srv = ServerThread(worker).start()
        remote = RemoteEngineWorker(
            "127.0.0.1", srv.port, replica_id="r0").start()
        try:
            req = make_req([1], 1)
            req.prompt = []  # invalid on the wire: parse rejects it
            res = run_request(remote, req)["result"]
            assert res.outcome == "rejected"
            assert "refused" in res.detail
        finally:
            remote.stop_polling()
            srv.stop()


class TestChildProcess:
    """Ring 2: real fake-replica children; crash and drain semantics."""

    def test_kill9_mid_stream_synthesizes_one_aborted(self):
        proc, port = spawn_fake_child("--token_delay_s", "0.05")
        remote = RemoteEngineWorker(
            "127.0.0.1", port, replica_id="r0", proc=proc,
            poll_interval_s=0.02).start()
        try:
            done = threading.Event()
            got = {"tokens": [], "dones": []}
            remote.submit(
                make_req([2, 7], 500),
                lambda rid, toks: got["tokens"].extend(toks),
                lambda res: (got["dones"].append(res), done.set()))
            deadline = time.monotonic() + 10
            while len(got["tokens"]) < 2 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert got["tokens"], "no tokens before the kill"
            remote.kill()
            assert done.wait(10)
            time.sleep(0.3)  # any late duplicate terminal would land now
            assert len(got["dones"]) == 1, "exactly one terminal"
            res = got["dones"][0]
            assert res.outcome == "aborted"
            # partial progress is preserved on the synthesized terminal
            assert res.tokens == got["tokens"]
            deadline = time.monotonic() + 5
            while remote.alive and time.monotonic() < deadline:
                time.sleep(0.02)
            assert not remote.alive
            assert remote.exit_code == -signal.SIGKILL
            assert remote.inflight == 0
        finally:
            remote.stop_polling()
            if proc.poll() is None:
                proc.kill()
            proc.wait(10)

    def test_drain_exits_zero(self):
        proc, port = spawn_fake_child()
        remote = RemoteEngineWorker(
            "127.0.0.1", port, replica_id="r0", proc=proc).start()
        try:
            res = run_request(remote, make_req([1, 2], 3))["result"]
            assert res.outcome == "ok"
            remote.shutdown(drain=True)
            remote.join(timeout=15)
            assert proc.poll() == 0, "clean drain must exit 0"
            assert remote.exit_code == 0
        finally:
            remote.stop_polling()
            if proc.poll() is None:
                proc.kill()
            proc.wait(10)

    def test_sigterm_drains_inflight_first(self):
        """SIGTERM mid-stream: the in-flight request still gets its
        real terminal (ok, full tokens), THEN the child exits 0."""
        proc, port = spawn_fake_child("--token_delay_s", "0.02")
        remote = RemoteEngineWorker(
            "127.0.0.1", port, replica_id="r0", proc=proc).start()
        try:
            done = threading.Event()
            got = {}
            remote.submit(
                make_req([4, 4], 20),
                lambda rid, toks: None,
                lambda res: (got.__setitem__("res", res), done.set()))
            time.sleep(0.1)  # a few tokens in
            proc.send_signal(signal.SIGTERM)
            assert done.wait(15)
            assert got["res"].outcome == "ok"
            assert len(got["res"].tokens) == 20
            proc.wait(15)
            assert proc.returncode == 0
        finally:
            remote.stop_polling()
            if proc.poll() is None:
                proc.kill()
            proc.wait(10)


class TestEngineParity:
    """Ring 3: a REAL engine behind the wire — bit-identical greedy
    tokens vs the same engine driven directly, one decode compile."""

    @pytest.fixture(scope="class")
    def tiny(self):
        import jax
        import jax.numpy as jnp

        from scaletorch_tpu.models import llama

        cfg = llama.LlamaConfig(
            vocab_size=64, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, dtype=jnp.float32)
        return cfg, llama.init_params(jax.random.PRNGKey(0), cfg)

    def _make_engine(self, tiny):
        from scaletorch_tpu.inference import InferenceEngine, SamplingParams

        cfg, params = tiny
        return InferenceEngine(
            params, cfg, max_slots=2, max_seq=32, prefill_len=16,
            sampling=SamplingParams(temperature=0.0),
            cache_layout="paged", page_size=4, strict_submit=False)

    def test_remote_bit_identical_one_compile(self, tiny):
        from scaletorch_tpu.serving.gateway import EngineWorker

        prompts = [[1, 2, 3], [7, 8, 9, 10], [4, 4, 4]]
        # oracle: the same engine driven directly
        oracle = self._make_engine(tiny)
        expect = {}
        for prompt in prompts:
            rid = oracle.submit(list(prompt), max_new_tokens=6)
            expect[tuple(prompt)] = oracle.run()[rid].tokens

        engine = self._make_engine(tiny)
        worker = EngineWorker(engine, replica_id="r0").start()
        srv = ServerThread(worker).start()
        remote = RemoteEngineWorker(
            "127.0.0.1", srv.port, replica_id="r0").start()
        try:
            for prompt in prompts:
                out = run_request(remote, make_req(prompt, 6), timeout=120)
                res = out["result"]
                assert res.outcome == "ok", res.detail
                assert res.tokens == expect[tuple(prompt)], prompt
                assert out["tokens"] == expect[tuple(prompt)], prompt
            assert engine.decode_compile_count == 1
            assert engine.prefill_compile_count == 1
            # the wire surfaces the compile count for CI to assert on
            metrics = remote._get_json("/metrics")
            assert metrics["decode_compile_count"] == 1
        finally:
            remote.stop_polling()
            srv.stop()
            worker.shutdown(drain=False)
            worker.join(timeout=10)
