"""Prefix-aware routing: key derivation, health, and the acceptance
attestation — with 2 in-process engine replicas and requests sharing a
system prompt, the radix-hash router achieves a strictly higher
aggregate prefix_hit_rate (and wastes fewer cold prefills) than the
consistent-hash-only baseline on the same schedule.
"""

import jax
import jax.numpy as jnp
import pytest

from scaletorch_tpu.inference import InferenceEngine, SamplingParams
from scaletorch_tpu.models import llama
from scaletorch_tpu.serving.router import (
    NoReplicaAvailable,
    PrefixAwareRouter,
    _rendezvous,
    page_chunk_hashes,
)

TINY = dict(
    vocab_size=64, hidden_size=32, intermediate_size=64,
    num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
    dtype=jnp.float32,
)
PAGE = 4


@pytest.fixture(scope="module")
def tiny_llama():
    cfg = llama.LlamaConfig(**TINY)
    return cfg, llama.init_params(jax.random.PRNGKey(0), cfg)


class TestChunkHashes:
    def test_shared_prefix_shares_hash_chain(self):
        a = page_chunk_hashes([1, 2, 3, 4, 5, 6, 7, 8, 9], PAGE)
        b = page_chunk_hashes([1, 2, 3, 4, 5, 6, 7, 8, 42, 43], PAGE)
        assert len(a) == 2 and len(b) == 2
        assert a == b  # identical full pages -> identical chains
        c = page_chunk_hashes([1, 2, 3, 4, 9, 9, 9, 9], PAGE)
        assert c[0] == a[0] and c[1] != a[1]  # diverge from page 2 on

    def test_cumulative_not_positional(self):
        # same second page after a DIFFERENT first page must not collide
        a = page_chunk_hashes([1, 2, 3, 4, 5, 6, 7, 8], PAGE)
        b = page_chunk_hashes([9, 9, 9, 9, 5, 6, 7, 8], PAGE)
        assert a[1] != b[1]

    def test_partial_page_never_hashes(self):
        assert page_chunk_hashes([1, 2, 3], PAGE) == []
        assert len(page_chunk_hashes([1, 2, 3, 4, 5], PAGE)) == 1

    def test_max_chunks_caps_chain(self):
        chain = page_chunk_hashes(list(range(100)), PAGE, max_chunks=3)
        assert len(chain) == 3


class TestRouterMembership:
    def test_learned_prefix_sticks(self):
        router = PrefixAwareRouter(["r0", "r1", "r2"], PAGE)
        prompt = [7] * 8 + [1, 2]
        first = router.route(prompt)
        for tail in ([3], [4, 5], [6]):
            assert router.route([7] * 8 + tail) == first

    def test_dead_replica_remaps_and_drops_owned_prefixes(self):
        router = PrefixAwareRouter(["r0", "r1"], PAGE)
        prompt = [3] * 8
        owner = router.route(prompt)
        router.mark_dead(owner, exit_code=44)
        survivor = router.route(prompt)
        assert survivor != owner
        assert router.alive() == [survivor]
        snap = router.snapshot()
        assert snap["router_replicas_dead"] == 1.0

    def test_exit_code_contract(self):
        router = PrefixAwareRouter(["r0", "r1"], PAGE)
        router.report_exit("r0", 0)     # clean drain: quiet removal
        assert router.replicas["r0"].exit_code == 0
        assert router.alive() == ["r1"]
        router.report_exit("r1", 43)    # crash: ejection
        assert router.replicas["r1"].exit_code == 43
        with pytest.raises(NoReplicaAvailable):
            router.route([1, 2, 3])

    def test_rendezvous_stability_under_membership_change(self):
        # keys NOT owned by the removed replica keep their assignment
        keys = [f"k{i}" for i in range(200)]
        before = {k: _rendezvous(k, ["a", "b", "c"]) for k in keys}
        after = {k: _rendezvous(k, ["a", "c"]) for k in keys}
        moved = [k for k in keys if before[k] != after[k]]
        assert all(before[k] == "b" for k in moved)

    def test_learn_owner_teaches_warmed_prefixes(self):
        """Warm rejoin: the gateway re-teaches ownership of chains a
        restarted replica pulled from a peer, so shared-prefix traffic
        routes back to it without a cold re-learn."""
        router = PrefixAwareRouter(["r0", "r1", "r2"], PAGE)
        chain = [5] * 8
        router.learn_owner(chain, "r1")
        for tail in ([1], [2, 3], []):
            assert router.route(chain + tail) == "r1"

    def test_learn_owner_ignores_dead_and_unknown_replicas(self):
        router = PrefixAwareRouter(["r0", "r1"], PAGE)
        chain = [5] * 8
        router.mark_dead("r1", exit_code=44)
        router.learn_owner(chain, "r1")      # dead: refused
        router.learn_owner(chain, "ghost")   # unknown: refused
        assert router.route(chain) == "r0"

    def test_learn_owner_noop_when_prefix_unaware(self):
        router = PrefixAwareRouter(["r0", "r1"], PAGE,
                                   prefix_aware=False)
        router.learn_owner([5] * 8, "r1")
        assert router.snapshot()["router_tracked_prefixes"] == 0.0

    def test_owner_map_is_lru_bounded(self):
        router = PrefixAwareRouter(["r0", "r1"], PAGE,
                                   max_tracked_prefixes=8)
        for i in range(50):
            router.route([i] * 8)
        assert router.snapshot()["router_tracked_prefixes"] <= 8


class TestHeadroomRouting:
    """Page-headroom-aware placement: weight cold rendezvous by free-
    page fraction when the pools diverge; never let prefix affinity
    pack a replica into exhaustion; rejoin restarted replicas cold."""

    def test_balanced_fleet_is_a_noop(self):
        plain = PrefixAwareRouter(["r0", "r1"], PAGE)
        aware = PrefixAwareRouter(["r0", "r1"], PAGE)
        hr = {"r0": 0.50, "r1": 0.62}  # spread < headroom_spread
        for i in range(100):
            prompt = [i, i + 1, i + 2]
            assert aware.route(prompt, headroom=hr) == plain.route(prompt)
        assert aware.snapshot()["router_routed_by_headroom"] == 0.0

    def test_imbalanced_cold_placement_follows_free_pages(self):
        router = PrefixAwareRouter(["r0", "r1"], PAGE)
        hr = {"r0": 0.05, "r1": 0.95}
        for i in range(200):
            router.route([1000 + i] * 8, headroom=hr)
        snap = router.snapshot()
        assert snap["router_routed_by_headroom"] > 0.0
        starved = router.replicas["r0"].dispatched
        free = router.replicas["r1"].dispatched
        assert free > 10 * starved, (starved, free)

    def test_affinity_override_only_below_floor(self):
        router = PrefixAwareRouter(["r0", "r1"], PAGE)
        owned = []
        for i in range(400):
            prompt = [2000 + i] * 8
            if router.route(prompt) == "r0":
                owned.append(prompt)
        assert len(owned) > 50
        # owner squeezed but still above the floor: affinity HOLDS
        # (spread 0.83 >= 0.25, so the fleet counts as imbalanced)
        hr = {"r0": 0.12, "r1": 0.95}
        for prompt in owned:
            assert router.route(prompt, headroom=hr) == "r0"
        # owner under the floor while the peer has room: most owned
        # prefixes are re-placed by the free-page weighting (weight
        # 0.02 vs 0.95 leaves a sliver on the owner — that's the point
        # of weighted rendezvous, not a bug)
        hr = {"r0": 0.02, "r1": 0.95}
        moved = sum(router.route(p, headroom=hr) == "r1" for p in owned)
        assert moved >= 0.9 * len(owned), (moved, len(owned))

    def test_missing_gauge_weighs_in_at_fleet_mean(self):
        # r2 just rejoined: no gauge yet. It must get real traffic
        # (mean weight), not be starved at the 1e-6 floor.
        router = PrefixAwareRouter(["r0", "r1", "r2"], PAGE)
        hr = {"r0": 0.9, "r1": 0.1}
        for i in range(300):
            router.route([3000 + i] * 8, headroom=hr)
        assert router.replicas["r2"].dispatched > 20

    def test_rejoin_is_cold_and_counted(self):
        router = PrefixAwareRouter(["r0", "r1"], PAGE)
        prompt = [9] * 8
        owner = router.route(prompt)
        router.mark_dead(owner, exit_code=44)
        survivor = router.route(prompt)
        assert survivor != owner
        router.rejoin(owner)
        assert sorted(router.alive()) == ["r0", "r1"]
        assert router.replicas[owner].exit_code is None
        # cold: the survivor LEARNED the prefix while the owner was
        # down, so affinity stays with the survivor after the rejoin
        assert router.route(prompt) == survivor
        assert router.snapshot()["router_rejoins"] == 1.0
        router.rejoin(owner)  # idempotent on a healthy replica
        assert router.snapshot()["router_rejoins"] == 1.0

    def test_weighted_rendezvous_minimal_disruption(self):
        from scaletorch_tpu.serving.router import _weighted_rendezvous

        keys = [f"k{i}" for i in range(400)]
        before = {k: _weighted_rendezvous(k, {"a": 1.0, "b": 1.0})
                  for k in keys}
        # doubling b's weight may only move keys TOWARD b
        after = {k: _weighted_rendezvous(k, {"a": 1.0, "b": 2.0})
                 for k in keys}
        moved = [k for k in keys if before[k] != after[k]]
        assert moved, "weight change must move some share"
        assert all(after[k] == "b" for k in moved)
        # equal weights spread roughly evenly
        share_a = sum(v == "a" for v in before.values()) / len(keys)
        assert 0.35 < share_a < 0.65
        # determinism
        assert all(
            _weighted_rendezvous(k, {"a": 1.0, "b": 2.0}) == after[k]
            for k in keys[:50])


def _run_schedule(tiny_llama, prefix_aware: bool, schedule):
    """Route + serve a schedule over two fresh replicas; return the
    aggregate (prefix_hit_rate, prefill_tokens_saved, cold_prefill_tokens)."""
    cfg, params = tiny_llama
    engines = {
        rid: InferenceEngine(
            params, cfg, max_slots=2, max_seq=32, prefill_len=16,
            sampling=SamplingParams(temperature=0.0),
            cache_layout="paged", page_size=PAGE, num_pages=64)
        for rid in ("r0", "r1")
    }
    router = PrefixAwareRouter(list(engines), PAGE,
                               prefix_aware=prefix_aware)
    for prompt in schedule:
        rid = router.route(prompt)
        engines[rid].submit(prompt, max_new_tokens=2)
        # serve as we go so earlier prompts' pages are registered in the
        # radix tree before later arrivals (steady-state serving order)
        engines[rid].run()
    admitted = sum(e.metrics.requests_admitted for e in engines.values())
    hits = sum(e.metrics.prefix_hits for e in engines.values())
    saved = sum(e.metrics.prefill_tokens_saved for e in engines.values())
    total_prompt = sum(len(p) for p in schedule)
    return hits / admitted, saved, total_prompt - saved


class TestPrefixRoutingBeatsConsistentHash:
    def test_acceptance_prefix_hit_rate_strictly_higher(self, tiny_llama):
        """The ISSUE acceptance gate. Two system prompts (2 pages each),
        each shared by several requests with unique tails; the tails are
        CHOSEN so the consistent-hash baseline provably scatters every
        group across both replicas (no lucky collisions)."""
        sys_a = [11, 12, 13, 14, 15, 16, 17, 18]
        sys_b = [21, 22, 23, 24, 25, 26, 27, 28]
        schedule = []
        for sys_prompt in (sys_a, sys_b):
            picked_by = {"r0": [], "r1": []}
            tail = 0
            while min(len(v) for v in picked_by.values()) < 3:
                tail += 1
                prompt = sys_prompt + [40 + tail % 20, 60 + tail % 4]
                target = _rendezvous(
                    "|".join(str(t) for t in prompt), ["r0", "r1"])
                if len(picked_by[target]) < 3:
                    picked_by[target].append(prompt)
            schedule.extend(picked_by["r0"] + picked_by["r1"])

        hit_rate_prefix, saved_prefix, cold_prefix = _run_schedule(
            tiny_llama, True, schedule)
        hit_rate_hash, saved_hash, cold_hash = _run_schedule(
            tiny_llama, False, schedule)

        # prefix-aware: each system prompt is cold exactly once -> 10 of
        # 12 admissions hit. Baseline: each group is split across both
        # replicas by construction -> at least 4 cold prefills.
        assert hit_rate_prefix > hit_rate_hash, \
            (hit_rate_prefix, hit_rate_hash)
        assert hit_rate_prefix >= 10 / 12
        assert saved_prefix > saved_hash
        assert cold_prefix < cold_hash  # fewer wasted cold-prefill tokens

    def test_greedy_outputs_identical_under_either_routing(self,
                                                           tiny_llama):
        """Routing changes WHERE a request decodes, never WHAT it
        decodes: results are bit-identical across routing modes."""
        cfg, params = tiny_llama
        sys_p = [11, 12, 13, 14, 15, 16, 17, 18]
        schedule = [sys_p + [40 + i] for i in range(4)]

        def run(prefix_aware):
            engines = {
                rid: InferenceEngine(
                    params, cfg, max_slots=2, max_seq=32, prefill_len=16,
                    sampling=SamplingParams(temperature=0.0),
                    cache_layout="paged", page_size=PAGE, num_pages=64)
                for rid in ("r0", "r1")
            }
            router = PrefixAwareRouter(list(engines), PAGE,
                                       prefix_aware=prefix_aware)
            outs = []
            for prompt in schedule:
                rid_engine = engines[router.route(prompt)]
                rid = rid_engine.submit(prompt, max_new_tokens=4)
                outs.append(rid_engine.run()[rid].tokens)
            return outs

        assert run(True) == run(False)
