"""SLO evaluation + the slo_check CI gate.

Pure host-side: the evaluation logic (serving/slo.py) with fake
quantiles, the checked-in tools/slo.json validating through the real
loader, and tools/slo_check.py end-to-end over synthesized telemetry
JSONL and a /metrics-shaped exposition.
"""

import json
import math
import os
import sys

import pytest

from scaletorch_tpu.serving.slo import (
    FAILURE_OUTCOMES,
    evaluate_slo,
    format_report,
    load_slo,
    parse_target_key,
    preset_targets,
    validate_preset,
)
from scaletorch_tpu.telemetry.histogram import LogHistogram

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, REPO)


class TestTargetGrammar:
    def test_parse_target_key(self):
        assert parse_target_key("ttft_p95_s") == ("ttft", 0.95)
        metric, q = parse_target_key("e2e_p99_9_s")
        assert metric == "e2e" and q == pytest.approx(0.999)
        assert parse_target_key("queue_wait_p50_s") == ("queue_wait", 0.5)

    @pytest.mark.parametrize("key", [
        "ttft", "ttft_p95", "p95_s", "ttft_p0_s", "ttft_p100_s",
        "ttft_p95_ms", "TTFT_p95_s",
    ])
    def test_bad_keys_raise(self, key):
        with pytest.raises(ValueError):
            parse_target_key(key)

    def test_validate_preset(self):
        validate_preset("x", {"error_budget": 0.1, "min_requests": 5,
                              "targets": {"ttft_p95_s": 1.0}})
        with pytest.raises(ValueError, match="error_budget"):
            validate_preset("x", {"error_budget": 2.0})
        with pytest.raises(ValueError, match="positive"):
            validate_preset("x", {"targets": {"ttft_p95_s": -1}})


class TestEvaluate:
    SPEC = {"min_requests": 2, "error_budget": 0.1,
            "targets": {"ttft_p95_s": 1.0, "tpot_p99_s": 0.5}}

    @staticmethod
    def quantiles(values):
        def fn(metric, q):
            return values.get(metric)
        return fn

    def test_all_green(self):
        result = evaluate_slo(
            self.SPEC, quantile_fn=self.quantiles({"ttft": 0.5,
                                                   "tpot": 0.1}),
            outcomes={"ok": 10})
        assert result["ok"] and not result["violations"]
        assert result["burn_rate"] == 0.0

    def test_latency_violation(self):
        result = evaluate_slo(
            self.SPEC, quantile_fn=self.quantiles({"ttft": 2.0}),
            outcomes={"ok": 10})
        assert not result["ok"]
        assert result["violations"] == ["ttft_p95_s"]
        # no tpot data -> skipped, never a violation
        tpot = [c for c in result["checks"] if c["name"] == "tpot_p99_s"]
        assert tpot[0].get("skipped")

    def test_error_budget_burn(self):
        # 2 timeouts in 10 = 20% > 10% budget -> burn 2.0
        result = evaluate_slo(
            self.SPEC, quantile_fn=self.quantiles({}),
            outcomes={"ok": 8, "timeout": 2})
        assert not result["ok"]
        assert "error_budget" in result["violations"]
        assert result["burn_rate"] == pytest.approx(2.0)

    def test_policy_outcomes_spend_no_budget(self):
        """shed/rejected/aborted are admission policy and client
        behavior — a load-shedding gateway is healthy, not failing."""
        assert set(FAILURE_OUTCOMES) == {"timeout", "quarantined"}
        result = evaluate_slo(
            self.SPEC, quantile_fn=self.quantiles({}),
            outcomes={"ok": 2, "shed": 50, "rejected": 5, "aborted": 3})
        assert result["ok"]

    def test_zero_budget_zero_tolerance(self):
        spec = dict(self.SPEC, error_budget=0.0)
        result = evaluate_slo(
            spec, quantile_fn=self.quantiles({}),
            outcomes={"ok": 9, "quarantined": 1})
        assert not result["ok"]
        assert math.isinf(result["burn_rate"])

    def test_insufficient_data_passes(self):
        result = evaluate_slo(
            self.SPEC, quantile_fn=self.quantiles({"ttft": 99.0}),
            outcomes={"timeout": 1})
        assert result["ok"] and result["insufficient_data"]
        assert result["checks"] == []

    def test_report_renders(self):
        result = evaluate_slo(
            self.SPEC, quantile_fn=self.quantiles({"ttft": 2.0}),
            outcomes={"ok": 10})
        text = format_report("tiny", result)
        assert "VIOLATION" in text and "ttft_p95_s" in text


class TestCheckedInFile:
    def test_tools_slo_json_valid_with_expected_presets(self):
        doc = load_slo(os.path.join(REPO, "tools", "slo.json"))
        tiny = preset_targets(doc, "tiny")
        assert tiny["error_budget"] == 0.0
        assert "ttft_p95_s" in tiny["targets"]
        preset_targets(doc, "production")
        with pytest.raises(ValueError, match="unknown SLO preset"):
            preset_targets(doc, "nope")


def write_jsonl(path, events):
    with open(path, "w") as f:
        for event in events:
            f.write(json.dumps(event) + "\n")


def access(outcome="ok", **kw):
    record = {"v": 1, "kind": "access", "time": 0.0, "proc": 0,
              "tenant": "default", "outcome": outcome, "status": 200,
              "trace_id": "ab" * 16, "queue_wait_s": 0.01,
              "ttft_s": 0.2, "e2e_s": 0.5, "tokens": 4,
              "prefix_hit": False, "replica": "r0"}
    record.update(kw)
    return record


class TestSloCheckCLI:
    def run_main(self, argv):
        from tools.slo_check import main
        return main(argv)

    def test_green_from_access_records(self, tmp_path, capsys):
        path = str(tmp_path / "events.jsonl")
        write_jsonl(path, [access() for _ in range(3)])
        rc = self.run_main(["--slo", os.path.join(REPO, "tools", "slo.json"),
                            "--preset", "tiny", path])
        assert rc == 0
        assert "OK" in capsys.readouterr().out

    def test_violation_exits_1(self, tmp_path, capsys):
        path = str(tmp_path / "events.jsonl")
        write_jsonl(path, [access(), access(outcome="timeout",
                                            status=504)])
        rc = self.run_main(["--slo", os.path.join(REPO, "tools", "slo.json"),
                            "--preset", "tiny", path])
        assert rc == 1
        assert "error_budget" in capsys.readouterr().out

    def test_histogram_records_cover_sample_free_metrics(self, tmp_path,
                                                        capsys):
        """tpot has no access-record scalar: the merged
        latency_histograms records must answer its quantile — and a
        slow TPOT must fail the gate."""
        h = LogHistogram()
        for _ in range(50):
            h.observe(8.0)  # way over tiny's tpot_p99_s=5.0
        path = str(tmp_path / "events.jsonl")
        write_jsonl(path, [
            access(),
            {"v": 1, "kind": "latency_histograms", "time": 0, "proc": 0,
             "tpot": {"default": h.to_dict()}},
        ])
        rc = self.run_main(["--slo", os.path.join(REPO, "tools", "slo.json"),
                            "--preset", "tiny", path])
        out = capsys.readouterr().out
        assert rc == 1 and "tpot_p99_s" in out

    def test_cumulative_histogram_snapshots_counted_once(self, tmp_path,
                                                         capsys):
        """The gateway re-emits its WHOLE histogram state every export
        cadence; slo_check must keep only the last snapshot per
        process, not merge every record (which multi-counts early
        observations — confirmed-bug regression)."""
        early = LogHistogram()
        for _ in range(32):
            early.observe(0.5)
        late = LogHistogram()
        for _ in range(32):
            late.observe(0.5)
        for _ in range(968):
            late.observe(0.01)  # steady state dominates the true p99
        path = str(tmp_path / "events.jsonl")
        write_jsonl(path, [
            access(),
            {"v": 1, "kind": "latency_histograms", "time": 0, "proc": 0,
             "tpot": {"default": early.to_dict()}},
            {"v": 1, "kind": "latency_histograms", "time": 1, "proc": 0,
             "tpot": {"default": late.to_dict()}},
        ])
        from tools.slo_check import collect, make_quantile_fn

        samples, merged, outcomes, prom = collect([path], None)
        assert merged["tpot"].count == 1000  # last snapshot, not 1032+
        q = make_quantile_fn(samples, merged, prom)
        assert q("tpot", 0.95) == pytest.approx(
            late.quantile(0.95), rel=0.01)
        capsys.readouterr()

    def test_refusal_samples_excluded_from_latency_quantiles(
            self, tmp_path):
        """Shed/rejected access records terminate in microseconds;
        their e2e samples must not dilute the served-latency quantiles
        (they still count as outcomes)."""
        path = str(tmp_path / "events.jsonl")
        write_jsonl(path, [
            access(e2e_s=5.0),
            *[access(outcome="shed", status=429, e2e_s=0.0001)
              for _ in range(50)],
        ])
        from tools.slo_check import collect

        samples, _, outcomes, _ = collect([path], None)
        assert samples["e2e"] == [5.0]
        assert outcomes["shed"] == 50  # outcomes keep counting

    def test_aborted_ttft_sample_kept_e2e_dropped(self, tmp_path):
        """An aborted stream's first token really arrived (ttft is
        stamped at token arrival, like the gateway histograms), but its
        truncated e2e must not feed the quantiles — keeps the access-
        sample source consistent with the histogram/scrape sources."""
        path = str(tmp_path / "events.jsonl")
        write_jsonl(path, [
            access(),
            access(outcome="aborted", status=503, ttft_s=0.9, e2e_s=1.0),
        ])
        from tools.slo_check import collect

        samples, _, _, _ = collect([path], None)
        assert sorted(samples["ttft"]) == [0.2, 0.9]
        assert samples["e2e"] == [0.5]

    def test_prom_label_values_containing_brace(self, tmp_path):
        """'}' is legal inside a quoted Prometheus label value and
        tenant names are untrusted — the scrape parser must not drop
        such a tenant's series (confirmed-bug regression)."""
        from scaletorch_tpu.telemetry.export import render_families
        from tools.slo_check import parse_prom_text

        h1, h2 = LogHistogram(), LogHistogram()
        for _ in range(2):
            h1.observe(0.1)
            h2.observe(0.2)
        text = render_families([
            {"name": "request_ttft_seconds", "type": "histogram",
             "series": [({"tenant": "a}b"}, h1), ({"tenant": "ok"}, h2)]},
        ])
        hists, _ = parse_prom_text(text)
        pairs = sorted(hists["ttft"]._by_le.items())
        assert pairs[-1][1] == 4  # +Inf cumulative covers BOTH tenants

    def test_outcomes_fall_back_to_gateway_metrics(self, tmp_path):
        path = str(tmp_path / "events.jsonl")
        write_jsonl(path, [
            {"v": 1, "kind": "gateway_metrics", "time": 0, "proc": 0,
             "http_ok": 5, "http_timeout": 5},
        ])
        rc = self.run_main(["--slo", os.path.join(REPO, "tools", "slo.json"),
                            "--preset", "tiny", path])
        assert rc == 1  # 50% timeouts against a zero budget

    def test_prom_scrape_source(self, tmp_path, capsys):
        """The acceptance path: reconstruct quantiles from the
        /metrics histogram exposition itself."""
        from scaletorch_tpu.telemetry.export import render_families

        h = LogHistogram()
        for v in (0.1, 0.2, 0.4):
            h.observe(v)
        text = render_families([
            {"name": "request_ttft_seconds", "type": "histogram",
             "series": [({"tenant": "default"}, h)]},
            {"name": "http_ok", "type": "counter", "samples": [(None, 3)]},
        ])
        prom = tmp_path / "metrics.txt"
        prom.write_text(text)
        rc = self.run_main(["--slo", os.path.join(REPO, "tools", "slo.json"),
                            "--preset", "tiny", "--prom", str(prom)])
        assert rc == 0
        out = capsys.readouterr().out
        assert "ttft_p95_s" in out and "OK" in out

    def test_usage_errors_exit_2(self, tmp_path, capsys):
        slo = os.path.join(REPO, "tools", "slo.json")
        assert self.run_main(["--slo", slo, "--preset", "tiny"]) == 2
        assert self.run_main(["--slo", slo, "--preset", "tiny",
                              str(tmp_path / "missing.jsonl")]) == 2
        assert self.run_main(["--slo", slo, "--preset", "nope",
                              str(tmp_path / "missing.jsonl")]) == 2
        bad = tmp_path / "bad.jsonl"
        bad.write_text("{not json\n")
        assert self.run_main(["--slo", slo, "--preset", "tiny",
                              str(bad)]) == 2
        capsys.readouterr()
