"""The replica supervisor: exit-code contract, backoff, flap, and the
tentpole attestation — conservation through kill -9.

Unit ring: scripted fake ``Popen`` objects drive the monitor loop
deterministically (seeded jitter rng) — drain-vs-crash exits, backoff
escalation and cap, flap detection, ready-timeout-as-crash, telemetry
event stream. Process ring: real fake-replica children (no jax in the
CHILD) under SIGTERM / SIGKILL / self-crash exit 44. Gateway ring: a
real ``ServingGateway`` over a supervised 2-child fleet takes a seeded
randomized kill -9 schedule mid-traffic — every HTTP request must
still get exactly one terminal, ``check_conservation()`` must hold,
the fleet must heal (restart, rejoin), and a follow-up request must
produce the exact expected tokens.
"""

import itertools
import json
import os
import random
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from scaletorch_tpu.serving.supervisor import ReplicaSupervisor

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
FAKE_REPLICA = os.path.join(TESTS_DIR, "fake_replica.py")

_PIDS = itertools.count(4000)


class FakeStdout:
    def __init__(self, lines):
        self._lines = list(lines)

    def readline(self):
        if self._lines:
            return self._lines.pop(0)
        return ""  # EOF

    def __iter__(self):
        return iter(())


class FakeProc:
    """A scripted Popen double the monitor loop can reap."""

    def __init__(self, *, ready=True, port=7001):
        self.pid = next(_PIDS)
        self.returncode = None
        self.stdout = FakeStdout(
            [f"READY port={port}\n"] if ready else [])
        self.terminated = False
        self.was_killed = False

    def exit(self, code):
        self.returncode = code

    def poll(self):
        return self.returncode

    def wait(self, timeout=None):
        if self.terminated and self.returncode is None:
            self.returncode = 0
        if self.was_killed and self.returncode is None:
            self.returncode = -9
        if self.returncode is None:
            raise RuntimeError("fake child still running")
        return self.returncode

    def terminate(self):
        self.terminated = True

    def kill(self):
        self.was_killed = True
        if self.returncode is None:
            self.returncode = -9


class RecordingExporter:
    def __init__(self):
        self.records = []

    def emit(self, kind, record):
        self.records.append((kind, dict(record)))


def make_supervisor(spawn_fn, ids=("r0",), **kw):
    kw.setdefault("poll_interval_s", 0.01)
    kw.setdefault("backoff_base_s", 0.02)
    kw.setdefault("backoff_max_s", 0.08)
    kw.setdefault("backoff_jitter", 0.0)
    kw.setdefault("ready_timeout_s", 2.0)
    kw.setdefault("rng", random.Random(0))
    return ReplicaSupervisor(spawn_fn, list(ids), **kw)


def wait_for(predicate, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.01)
    raise AssertionError(f"timed out waiting for {msg}")


class TestExitCodeContract:
    """Unit ring: scripted fake processes, deterministic jitter."""

    def test_exit_zero_is_drained_no_restart(self):
        procs = []

        def spawn(rid):
            procs.append(FakeProc())
            return procs[-1]

        exits = []
        sup = make_supervisor(spawn, on_exit=lambda rid, rc:
                              exits.append((rid, rc)))
        sup.start()
        assert sup.replica_status("r0")["state"] == "up"
        procs[0].exit(0)
        wait_for(lambda: sup.replica_status("r0")["state"] == "drained",
                 msg="drained state")
        time.sleep(0.1)  # give a buggy restart a chance to fire
        assert len(procs) == 1, "exit 0 must NOT respawn"
        assert exits == [("r0", 0)]
        assert sup.replica_status("r0")["restarts_total"] == 0
        sup.stop(drain=False)

    @pytest.mark.parametrize("code", [42, 43, 44, -9, 1])
    def test_crash_family_restarts_with_backoff(self, code):
        procs = []

        def spawn(rid):
            procs.append(FakeProc())
            return procs[-1]

        restarts = []
        sup = make_supervisor(
            spawn, on_restart=lambda rid, w: restarts.append(rid))
        sup.start()
        first_pid = sup.replica_status("r0")["pid"]
        procs[0].exit(code)
        wait_for(lambda: len(procs) == 2, msg="respawn")
        wait_for(lambda: sup.replica_status("r0")["state"] == "up",
                 msg="back up")
        st = sup.replica_status("r0")
        assert st["restarts_total"] == 1
        assert st["last_exit_code"] == code
        assert st["pid"] != first_pid
        assert restarts == ["r0"]
        sup.stop(drain=False)

    def test_backoff_escalates_and_caps(self):
        sup = make_supervisor(lambda rid: FakeProc(), backoff_base_s=0.5,
                              backoff_max_s=4.0)
        assert sup._backoff_s(1) == 0.5
        assert sup._backoff_s(2) == 1.0
        assert sup._backoff_s(3) == 2.0
        assert sup._backoff_s(4) == 4.0
        assert sup._backoff_s(10) == 4.0  # capped
        jittered = make_supervisor(
            lambda rid: FakeProc(), backoff_base_s=1.0, backoff_max_s=8.0,
            backoff_jitter=0.5, rng=random.Random(7))
        samples = [jittered._backoff_s(1) for _ in range(50)]
        assert all(1.0 <= s <= 1.5 for s in samples)
        assert len(set(samples)) > 1, "jitter must actually vary"

    def test_flapping_marks_failed_permanently(self):
        procs = []

        def spawn(rid):
            procs.append(FakeProc())
            return procs[-1]

        sup = make_supervisor(spawn, flap_window_s=60.0,
                              flap_max_restarts=3)
        sup.start()

        def crash_latest():
            procs[-1].exit(42)

        for _ in range(2):
            n = len(procs)
            crash_latest()
            wait_for(lambda: len(procs) == n + 1, msg="respawn")
            wait_for(lambda: sup.replica_status("r0")["state"] == "up",
                     msg="back up")
        crash_latest()  # 3rd crash inside the window -> flapping
        wait_for(lambda: sup.replica_status("r0")["state"] == "failed",
                 msg="failed state")
        spawned = len(procs)
        time.sleep(0.15)
        assert len(procs) == spawned, "failed replica must not respawn"
        assert sup.replica_status("r0")["restarts_total"] == 2
        sup.stop(drain=False)

    def test_healthy_uptime_resets_consecutive(self):
        procs = []

        def spawn(rid):
            procs.append(FakeProc())
            return procs[-1]

        # healthy_reset_s=0: every uptime counts as healthy, so the
        # backoff exponent never escalates while total keeps counting
        sup = make_supervisor(spawn, healthy_reset_s=0.0,
                              flap_window_s=0.01, flap_max_restarts=100)
        sup.start()
        for n in (1, 2):
            procs[-1].exit(42)
            wait_for(lambda: len(procs) == n + 1, msg="respawn")
            wait_for(lambda: sup.replica_status("r0")["state"] == "up",
                     msg="back up")
            st = sup.replica_status("r0")
            assert st["restarts_consecutive"] == 1
            assert st["restarts_total"] == n
        sup.stop(drain=False)

    def test_first_boot_failure_raises(self):
        with pytest.raises(RuntimeError, match="first boot"):
            make_supervisor(
                lambda rid: FakeProc(ready=False), ready_timeout_s=0.5
            ).start()

    def test_telemetry_event_stream(self):
        procs = []

        def spawn(rid):
            procs.append(FakeProc())
            return procs[-1]

        exp = RecordingExporter()
        sup = make_supervisor(spawn, exporter=exp)
        sup.start()
        procs[0].exit(44)
        wait_for(lambda: len(procs) == 2, msg="respawn")
        wait_for(lambda: sup.replica_status("r0")["state"] == "up",
                 msg="back up")
        sup.stop(drain=False)
        assert all(kind == "supervisor" for kind, _ in exp.records)
        events = [r["event"] for _, r in exp.records]
        assert events[:2] == ["spawn", "ready"]
        assert "crash" in events and "restart" in events
        crash = next(r for _, r in exp.records if r["event"] == "crash")
        assert crash["exit_code"] == 44
        assert crash["replica"] == "r0"
        assert crash["backoff_s"] >= 0


class TestRealChildren:
    """Process ring: real (jax-free) fake-replica children."""

    def _spawner(self, *extra):
        env = dict(os.environ)
        env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
            TESTS_DIR)) + os.pathsep + env.get("PYTHONPATH", "")

        def spawn(rid):
            return subprocess.Popen(
                [sys.executable, FAKE_REPLICA, "--replica_id", rid,
                 *extra],
                stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                text=True, env=env)

        return spawn

    def test_drain_vs_crash_exit_codes(self):
        sup = make_supervisor(self._spawner(), ids=("a", "b"),
                              ready_timeout_s=30.0)
        sup.start()
        try:
            status = sup.status()
            assert {status["a"]["state"], status["b"]["state"]} == {"up"}
            with sup._lock:
                proc_a = sup._replicas["a"].proc
                proc_b = sup._replicas["b"].proc
            # SIGTERM -> clean drain, exit 0, no restart
            proc_a.terminate()
            wait_for(lambda: sup.replica_status("a")["state"] == "drained",
                     timeout=20, msg="a drained")
            assert sup.replica_status("a")["last_exit_code"] == 0
            assert sup.replica_status("a")["restarts_total"] == 0
            # SIGKILL -> crash family, restarted with a NEW pid
            old_pid = sup.replica_status("b")["pid"]
            proc_b.kill()
            wait_for(lambda: sup.replica_status("b")["restarts_total"] == 1,
                     timeout=20, msg="b restarted")
            wait_for(lambda: sup.replica_status("b")["state"] == "up",
                     timeout=20, msg="b back up")
            st = sup.replica_status("b")
            assert st["last_exit_code"] == -signal.SIGKILL
            assert st["pid"] not in (None, old_pid)
        finally:
            sup.stop(drain=False)

    def test_selfcrash_exit_code_recorded_and_restarted(self):
        sup = make_supervisor(
            self._spawner("--selfcrash_after_s", "0.3",
                          "--selfcrash_code", "44"),
            ready_timeout_s=30.0, flap_max_restarts=50,
            flap_window_s=0.001)
        sup.start()
        try:
            wait_for(lambda:
                     sup.replica_status("r0")["restarts_total"] >= 1,
                     timeout=20, msg="restart after exit 44")
            assert sup.replica_status("r0")["last_exit_code"] == 44
        finally:
            sup.stop(drain=False)


class TestGatewayConservationUnderCrashes:
    """Gateway ring: randomized kill -9 schedule vs a supervised fleet.

    The tentpole invariant: ``http_requests_received == sum(outcomes)``
    survives replica processes dying mid-stream, and the fleet heals.
    """

    def _build(self, tmp_path):
        from scaletorch_tpu.serving.gateway import ServingGateway
        from scaletorch_tpu.serving.remote import RemoteEngineWorker

        env = dict(os.environ)
        env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
            TESTS_DIR)) + os.pathsep + env.get("PYTHONPATH", "")

        def spawn(rid):
            return subprocess.Popen(
                [sys.executable, FAKE_REPLICA, "--replica_id", rid,
                 "--token_delay_s", "0.01"],
                stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                text=True, env=env)

        sup = ReplicaSupervisor(
            spawn, ["r0", "r1"],
            worker_factory=lambda rid, port, proc: RemoteEngineWorker(
                "127.0.0.1", port, replica_id=rid, proc=proc,
                poll_interval_s=0.03).start(),
            poll_interval_s=0.01, backoff_base_s=0.05, backoff_max_s=0.2,
            backoff_jitter=0.0, flap_window_s=0.5, flap_max_restarts=20,
            ready_timeout_s=30.0, rng=random.Random(0))
        workers = sup.start()
        gw = ServingGateway(workers, port=0, supervisor=sup,
                            max_backlog=512).start_in_thread()
        return gw, sup

    def test_conservation_through_randomized_kill9(self, tmp_path):
        from .fake_replica import FakeEngineWorker

        gw, sup = self._build(tmp_path)
        rng = random.Random(1234)
        stop_killing = threading.Event()
        kills = []

        def killer():
            while not stop_killing.is_set():
                time.sleep(rng.uniform(0.15, 0.4))
                if stop_killing.is_set():
                    break  # no straggler kill after the clients finish
                with sup._lock:
                    up = [r for r in sup._replicas.values()
                          if r.state == "up" and r.proc is not None
                          and r.proc.poll() is None]
                if not up:
                    continue
                victim = rng.choice(up)
                victim.proc.kill()
                kills.append(victim.replica_id)

        outcomes = []

        def client(seed):
            crng = random.Random(seed)
            for _ in range(6):
                prompt = [crng.randrange(1, 50)
                          for _ in range(crng.randrange(1, 5))]
                body = json.dumps({
                    "prompt": prompt,
                    "max_new_tokens": crng.randrange(4, 30),
                    "stream": False}).encode()
                req = urllib.request.Request(
                    f"http://127.0.0.1:{gw.port}/v1/generate",
                    data=body, method="POST")
                try:
                    resp = urllib.request.urlopen(req, timeout=30)
                    payload = json.loads(resp.read())
                except urllib.error.HTTPError as err:
                    payload = json.loads(err.read())
                outcomes.append(payload["outcome"])

        kt = threading.Thread(target=killer, daemon=True)
        kt.start()
        clients = [threading.Thread(target=client, args=(s,), daemon=True)
                   for s in range(4)]
        try:
            for t in clients:
                t.start()
            for t in clients:
                t.join(timeout=120)
                assert not t.is_alive(), "client wedged without terminal"
            stop_killing.set()
            kt.join(timeout=5)

            # every request got exactly one terminal outcome
            assert len(outcomes) == 24
            assert kills, "the schedule never actually killed a child"
            # the ledger balances THROUGH the crashes
            gw.metrics.check_conservation()
            # the fleet healed: kills were restarted. Require a LIVE
            # process, not just state "up" — a corpse the monitor has
            # not reaped yet still reads "up" for a poll interval.
            def healed():
                with sup._lock:
                    return all(r.state == "up" and r.proc is not None
                               and r.proc.poll() is None
                               for r in sup._replicas.values())

            wait_for(healed, timeout=30, msg="fleet healed")
            total_restarts = sum(st["restarts_total"]
                                 for st in sup.status().values())
            assert total_restarts >= 1
            # and a restarted fleet still serves CORRECT tokens
            oracle = FakeEngineWorker()
            body = json.dumps({"prompt": [11, 7], "max_new_tokens": 5,
                               "stream": False}).encode()
            resp = urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{gw.port}/v1/generate", data=body,
                method="POST"), timeout=30)
            payload = json.loads(resp.read())
            assert payload["outcome"] == "ok"
            assert payload["token_ids"] == \
                oracle.expected_tokens([11, 7], 5)
            # process state is on /healthz
            health = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{gw.port}/healthz",
                timeout=10).read())
            for rid in ("r0", "r1"):
                rep = health["replicas"][rid]
                assert rep["state"] == "up"
                assert isinstance(rep["pid"], int)
                assert rep["restarts_total"] >= 0
            # ...and on /metrics as a labelled counter
            metrics = urllib.request.urlopen(
                f"http://127.0.0.1:{gw.port}/metrics",
                timeout=10).read().decode()
            assert "replica_restarts_total" in metrics
            assert 'replica_up{replica="r0"}' in metrics
        finally:
            stop_killing.set()
            gw.stop_sync()
            sup.stop(drain=False)
