"""Warm rejoin: the peer-to-peer prefix transfer wire and its drills.

Three rings, inside out: (1) the framing alone — checksummed
length-prefixed frames round-trip, corruption is detected, truncation
reads as a snapped stream; (2) the transfer wire in-process — a
``ReplicaServer`` donor over the jax-free ``FakeEngineWorker`` streams
``/prefix_map`` + ``/warm`` to ``pull_warm_state``, including the
corrupt-chunk drill (drop that chunk, keep the rest) and resume; (3)
real child processes — a donor SIGKILL'd mid-transfer degrades to the
next peer then cold, the UDS transport carries both dispatch and warm
traffic, and a supervised gateway fleet under a randomized kill -9
schedule warms restarted replicas while conserving every HTTP request.
"""

import http.client
import json
import os
import random
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from scaletorch_tpu.inference.resilience import ServingFaultInjector
from scaletorch_tpu.serving import protocol
from scaletorch_tpu.serving.protocol import ProtocolError
from scaletorch_tpu.serving.remote import (
    RemoteEngineWorker,
    ReplicaServer,
    _transfer_pages,
    pull_warm_state,
)

from .fake_replica import FakeEngineWorker

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
FAKE_REPLICA = os.path.join(TESTS_DIR, "fake_replica.py")
CHAIN = [1, 2, 3, 5, 8, 13, 21, 34]  # two full pages at page_size=4


def child_env():
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(
        os.path.dirname(TESTS_DIR)) + os.pathsep + env.get("PYTHONPATH", "")
    return env


def spawn_child(*extra_args):
    """fake_replica.py child; returns (proc, port_or_uds_path)."""
    proc = subprocess.Popen(
        [sys.executable, FAKE_REPLICA, *extra_args],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=child_env())
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            raise RuntimeError(
                f"fake replica died before READY rc={proc.poll()}")
        if line.startswith("READY port="):
            return proc, int(line.strip().split("=", 1)[1])
        if line.startswith("READY uds="):
            return proc, line.strip().split("=", 1)[1]
    raise RuntimeError("fake replica never printed READY")


class ServerThread:
    """An in-process ReplicaServer on its own event-loop thread."""

    def __init__(self, worker, *, uds=None, injector=None):
        self.worker = worker
        self.uds = uds
        self.injector = injector
        self.server = None
        self.port = None
        self._loop = None
        self._started = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="warm-server-test", daemon=True)

    def _run(self):
        import asyncio

        async def main():
            self._loop = asyncio.get_running_loop()
            self.server = ReplicaServer(
                self.worker, port=0, uds=self.uds,
                injector=self.injector)
            await self.server.start()
            self.port = self.server.port
            self._started.set()
            await self.server.wait_drain()
            await self.server.close()

        asyncio.run(main())

    def start(self):
        self._thread.start()
        assert self._started.wait(10), "replica server never bound"
        return self

    def stop(self):
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self.server.request_drain)
        self._thread.join(10)


def get_json(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read().decode())
    finally:
        conn.close()


def wait_for(predicate, timeout=15.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


class TestWarmFrames:
    """Ring 1: the framing alone, no sockets."""

    def test_frame_roundtrip(self, tmp_path):
        payload = protocol.encode_warm_page_payload(7, b"kkkk", b"vvvv")
        frame = protocol.encode_warm_frame(3, payload)
        path = tmp_path / "frames.bin"
        path.write_bytes(frame + protocol.encode_warm_frame(
            protocol.WARM_END_INDEX, b""))
        with open(path, "rb") as fp:
            index, got, ok = protocol.read_warm_frame(fp)
            assert (index, ok) == (3, True)
            assert protocol.decode_warm_page_payload(got) == \
                (7, b"kkkk", b"vvvv")
            index, got, ok = protocol.read_warm_frame(fp)
            assert index == protocol.WARM_END_INDEX and ok
            assert protocol.read_warm_frame(fp) is None  # EOF

    def test_corruption_is_detected_not_raised(self, tmp_path):
        frame = protocol.corrupt_warm_frame(
            protocol.encode_warm_frame(1, b"payload-bytes"))
        path = tmp_path / "bad.bin"
        path.write_bytes(frame)
        with open(path, "rb") as fp:
            index, _payload, ok = protocol.read_warm_frame(fp)
        assert index == 1 and ok is False

    def test_truncated_stream_reads_as_snapped(self, tmp_path):
        frame = protocol.encode_warm_frame(2, b"x" * 64)
        path = tmp_path / "cut.bin"
        path.write_bytes(frame[: len(frame) - 10])
        with open(path, "rb") as fp:
            assert protocol.read_warm_frame(fp) is None

    def test_page_payload_length_mismatch_raises(self):
        payload = protocol.encode_warm_page_payload(1, b"abc", b"de")
        with pytest.raises(ProtocolError):
            protocol.decode_warm_page_payload(payload[:-1])


class TestDonorWire:
    """Ring 2: donor endpoints + the pull client, in-process."""

    def test_prefix_map_endpoint(self):
        worker = FakeEngineWorker(page_size=4)
        assert worker.seed_prefix(CHAIN + [55]) == 2  # partial page shed
        srv = ServerThread(worker).start()
        try:
            status, pmap = get_json(srv.port, "/prefix_map")
            assert status == 200
            assert pmap["page_size"] == 4
            assert pmap["dtype"] == "uint8"
            chain = pmap["chains"][0]
            assert chain["tokens"] == CHAIN
            assert chain["pages"] == [0, 1]
            # page-aligned cumulative hashes ride the map for the router
            assert len(chain["hashes"]) == 2
            assert pmap["pages"]["0"]["frozen"] is True
        finally:
            srv.stop()

    def test_prefix_map_without_surface_is_empty(self):
        worker = FakeEngineWorker(page_size=4)
        worker.prefix_map = None  # a replica with no paged prefix state
        srv = ServerThread(worker).start()
        try:
            status, pmap = get_json(srv.port, "/prefix_map")
            assert status == 200
            assert pmap["chains"] == [] and pmap["pages"] == {}
        finally:
            srv.stop()

    def test_warm_stream_is_bit_identical(self):
        worker = FakeEngineWorker(page_size=4)
        worker.seed_prefix(CHAIN)
        srv = ServerThread(worker).start()
        try:
            contents = {}
            dropped, _next, completed = _transfer_pages(
                {"host": "127.0.0.1", "port": srv.port}, [0, 1], 1,
                contents, timeout=10)
            assert (dropped, completed) == (0, True)
            assert contents == {0: worker.page_bytes(0, 4),
                                1: worker.page_bytes(1, 4)}
        finally:
            srv.stop()

    def test_resume_skips_delivered_chunks(self):
        worker = FakeEngineWorker(page_size=4)
        worker.seed_prefix(CHAIN)
        srv = ServerThread(worker).start()
        try:
            contents = {}
            _d, _n, completed = _transfer_pages(
                {"host": "127.0.0.1", "port": srv.port}, [0, 1], 2,
                contents, timeout=10)
            assert completed
            assert list(contents) == [1]  # chunk 1 was never re-sent
        finally:
            srv.stop()

    def test_corrupt_chunk_dropped_rest_kept(self):
        worker = FakeEngineWorker(page_size=4)
        worker.seed_prefix(CHAIN)
        srv = ServerThread(
            worker,
            injector=ServingFaultInjector(gw_warm_corrupt_chunk_at=1),
        ).start()
        try:
            contents = {}
            dropped, _n, completed = _transfer_pages(
                {"host": "127.0.0.1", "port": srv.port}, [0, 1], 1,
                contents, timeout=10)
            assert (dropped, completed) == (1, True)
            assert list(contents) == [1]  # chunk 2 survived the drill
        finally:
            srv.stop()


class TestPullWarmState:
    """Ring 2 continued: the full pull, recipient import, degradation."""

    def test_pull_warms_recipient(self):
        donor = FakeEngineWorker(page_size=4)
        donor.seed_prefix(CHAIN)
        srv = ServerThread(donor).start()
        recipient = FakeEngineWorker(page_size=4)
        try:
            summary = pull_warm_state(
                recipient,
                [{"host": "127.0.0.1", "port": srv.port,
                  "replica": "rd"}],
                backoff_s=0.01)
            assert summary["status"] == "warmed"
            assert summary["donor"] == "rd"
            assert summary["pages"] == 2
            assert summary["chains"] == [CHAIN]
            assert summary["chunks_dropped"] == 0
            assert recipient.gauges()["warm_pages_total"] == 2.0
            # bit parity: the recipient now holds the donor's bytes
            _meta, got = recipient.export_prefix_pages([0, 1])
            assert got == {0: donor.page_bytes(0, 4),
                           1: donor.page_bytes(1, 4)}
            assert recipient._has_warm_prefix(CHAIN + [99])
        finally:
            srv.stop()

    def test_no_peers_is_cold(self):
        recipient = FakeEngineWorker(page_size=4)
        summary = pull_warm_state(recipient, [], backoff_s=0.01)
        assert summary["status"] == "cold"
        assert summary["attempts"] == 0
        assert recipient.gauges()["warm_pages_total"] == 0.0

    def test_unreachable_donor_is_cold(self):
        recipient = FakeEngineWorker(page_size=4)
        summary = pull_warm_state(
            recipient, [{"host": "127.0.0.1", "port": 1}],
            attempts_per_donor=2, backoff_s=0.01)
        assert summary["status"] == "cold"
        assert summary["attempts"] == 2  # retried with backoff first

    def test_empty_donor_falls_through_to_next_peer(self):
        cold_donor = FakeEngineWorker(page_size=4)  # nothing to give
        warm_donor = FakeEngineWorker(page_size=4)
        warm_donor.seed_prefix(CHAIN)
        s1 = ServerThread(cold_donor).start()
        s2 = ServerThread(warm_donor).start()
        recipient = FakeEngineWorker(page_size=4)
        try:
            summary = pull_warm_state(
                recipient,
                [{"host": "127.0.0.1", "port": s1.port, "replica": "a"},
                 {"host": "127.0.0.1", "port": s2.port, "replica": "b"}],
                backoff_s=0.01)
            assert summary["status"] == "warmed"
            assert summary["donor"] == "b"
        finally:
            s1.stop()
            s2.stop()

    def test_corrupt_tail_imports_valid_prefix(self):
        donor = FakeEngineWorker(page_size=4)
        donor.seed_prefix(CHAIN)
        srv = ServerThread(
            donor,
            injector=ServingFaultInjector(gw_warm_corrupt_chunk_at=2),
        ).start()
        recipient = FakeEngineWorker(page_size=4)
        try:
            summary = pull_warm_state(
                recipient,
                [{"host": "127.0.0.1", "port": srv.port}],
                backoff_s=0.01)
            # the stream completed (drop chunk, keep the rest), so the
            # import keeps the chain's valid one-page prefix
            assert summary["status"] == "warmed"
            assert summary["chunks_dropped"] == 1
            assert summary["pages"] == 1
            assert summary["chains"] == [CHAIN[:4]]
        finally:
            srv.stop()

    def test_incompatible_pool_imports_nothing(self):
        donor = FakeEngineWorker(page_size=4)
        donor.seed_prefix(CHAIN)
        srv = ServerThread(donor).start()
        recipient = FakeEngineWorker(page_size=8)  # pool mismatch
        try:
            summary = pull_warm_state(
                recipient,
                [{"host": "127.0.0.1", "port": srv.port}],
                backoff_s=0.01)
            assert summary["pages"] == 0
            assert recipient.gauges()["warm_pages_total"] == 0.0
        finally:
            srv.stop()


class TestWarmChildren:
    """Ring 3: real child processes — donor death, UDS, warm_start."""

    def test_donor_crash_falls_back_to_next_peer(self):
        chain_arg = ",".join(str(t) for t in CHAIN)
        # the flaky donor corrupts chunk 1 AND dies right after it, so
        # it delivers nothing useful before the stream snaps
        flaky, flaky_port = spawn_child(
            "--replica_id", "flaky", "--warm_chain", chain_arg,
            "--ft_gw_warm_corrupt_chunk_at", "1",
            "--ft_gw_warm_donor_crash_at", "1")
        steady, steady_port = spawn_child(
            "--replica_id", "steady", "--warm_chain", chain_arg)
        recipient = FakeEngineWorker(page_size=4)
        try:
            summary = pull_warm_state(
                recipient,
                [{"host": "127.0.0.1", "port": flaky_port,
                  "replica": "flaky"},
                 {"host": "127.0.0.1", "port": steady_port,
                  "replica": "steady"}],
                attempts_per_donor=2, backoff_s=0.01)
            assert summary["status"] == "warmed"
            assert summary["donor"] == "steady"
            assert summary["pages"] == 2
            assert recipient._has_warm_prefix(CHAIN)
            wait_for(lambda: flaky.poll() is not None,
                     msg="flaky donor died")
            assert flaky.poll() == -9  # the drill IS a SIGKILL
        finally:
            for proc in (flaky, steady):
                proc.kill()
                proc.wait(timeout=10)

    def test_crashing_only_donor_degrades_to_cold(self):
        chain_arg = ",".join(str(t) for t in CHAIN)
        flaky, flaky_port = spawn_child(
            "--replica_id", "flaky", "--warm_chain", chain_arg,
            "--ft_gw_warm_corrupt_chunk_at", "1",
            "--ft_gw_warm_donor_crash_at", "1")
        recipient = FakeEngineWorker(page_size=4)
        try:
            summary = pull_warm_state(
                recipient,
                [{"host": "127.0.0.1", "port": flaky_port,
                  "replica": "flaky"}],
                attempts_per_donor=2, backoff_s=0.01)
            assert summary["status"] == "cold"
            assert summary["pages"] == 0
            assert recipient.gauges()["warm_pages_total"] == 0.0
        finally:
            flaky.kill()
            flaky.wait(timeout=10)

    def test_uds_transport_serves_and_warms(self, tmp_path):
        chain_arg = ",".join(str(t) for t in CHAIN)
        sock = str(tmp_path / "donor.sock")
        proc, path = spawn_child(
            "--replica_id", "uds0", "--uds", sock,
            "--warm_chain", chain_arg, "--token_delay_s", "0.0")
        assert path == sock
        remote = RemoteEngineWorker(
            "127.0.0.1", 0, replica_id="uds0", uds=sock).start()
        recipient = FakeEngineWorker(page_size=4)
        try:
            assert remote.alive
            assert remote.address == {"uds": sock, "replica": "uds0"}
            # dispatch rides the socket
            from .test_remote import make_req, run_request

            out = run_request(remote, make_req([3, 1, 4], 6))
            oracle = FakeEngineWorker()
            assert out["result"].outcome == "ok"
            assert out["tokens"] == oracle.expected_tokens([3, 1, 4], 6)
            # ...and so does the warm transfer
            summary = pull_warm_state(
                recipient, [{"uds": sock, "replica": "uds0"}],
                backoff_s=0.01)
            assert summary["status"] == "warmed"
            assert summary["pages"] == 2
        finally:
            remote.stop_polling()
            proc.kill()
            proc.wait(timeout=10)

    def test_warm_start_endpoint_pulls_and_reports(self):
        chain_arg = ",".join(str(t) for t in CHAIN)
        donor, donor_port = spawn_child(
            "--replica_id", "donor", "--warm_chain", chain_arg)
        cold, cold_port = spawn_child("--replica_id", "cold")
        remote = RemoteEngineWorker(
            "127.0.0.1", cold_port, replica_id="cold").start()
        try:
            summary = remote.warm_start(
                [{"host": "127.0.0.1", "port": donor_port,
                  "replica": "donor"}], backoff_s=0.01)
            assert summary["status"] == "warmed"
            assert summary["pages"] == 2
            # the warmed state is visible on the replica's health surface
            _status, health = get_json(cold_port, "/healthz")
            assert health["warm_pages"] == 2
            assert health["prefix_pages"] == 2
        finally:
            remote.stop_polling()
            for proc in (donor, cold):
                proc.kill()
                proc.wait(timeout=10)


class RecordingExporter:
    def __init__(self):
        self.records = []
        self._lock = threading.Lock()

    def emit(self, kind, record):
        with self._lock:
            self.records.append((kind, dict(record)))

    def of_kind(self, kind):
        with self._lock:
            return [r for k, r in self.records if k == kind]


class TestWarmGatewayFleet:
    """Ring 3 continued: the supervised fleet warms restarted replicas
    concurrently with readiness, and conservation holds throughout."""

    def _build(self, *, warm_rids=("r0", "r1"), exporter=None):
        from scaletorch_tpu.serving.gateway import ServingGateway
        from scaletorch_tpu.serving.supervisor import ReplicaSupervisor

        chain_arg = ",".join(str(t) for t in CHAIN)
        env = child_env()

        def spawn(rid):
            cmd = [sys.executable, FAKE_REPLICA, "--replica_id", rid,
                   "--token_delay_s", "0.01"]
            if rid in warm_rids:
                cmd += ["--warm_chain", chain_arg]
            return subprocess.Popen(
                cmd, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                text=True, env=env)

        sup = ReplicaSupervisor(
            spawn, ["r0", "r1"],
            worker_factory=lambda rid, port, proc: RemoteEngineWorker(
                "127.0.0.1", port, replica_id=rid, proc=proc,
                poll_interval_s=0.03).start(),
            poll_interval_s=0.01, backoff_base_s=0.05, backoff_max_s=0.2,
            backoff_jitter=0.0, flap_window_s=0.5, flap_max_restarts=30,
            ready_timeout_s=30.0, rng=random.Random(0))
        workers = sup.start()
        gw = ServingGateway(workers, port=0, supervisor=sup,
                            max_backlog=512,
                            exporter=exporter).start_in_thread()
        return gw, sup

    def _kill_child(self, sup, rid):
        with sup._lock:
            rep = sup._replicas[rid]
            assert rep.proc is not None
            rep.proc.kill()

    def test_restart_warms_from_peer(self):
        exporter = RecordingExporter()
        # only r0 can donate: the restarted r1 must get ITS pages
        gw, sup = self._build(warm_rids=("r0",), exporter=exporter)
        try:
            self._kill_child(sup, "r1")
            wait_for(lambda: all(
                st["state"] == "up" for st in sup.status().values()),
                timeout=30, msg="fleet healed")
            wait_for(
                lambda: any(r.get("replica") == "r1"
                            for r in exporter.of_kind("warmup")),
                timeout=30, msg="warmup event")
            record = [r for r in exporter.of_kind("warmup")
                      if r.get("replica") == "r1"][0]
            assert record["status"] == "warmed"
            assert record["donor"] == "r0"
            assert record["pages"] == 2
            # the warmed pages surface on the gateway's health + metrics
            health = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{gw.port}/healthz",
                timeout=10).read())
            wait_for(lambda: json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{gw.port}/healthz", timeout=10
            ).read())["replicas"]["r1"].get("warm_pages") == 2,
                timeout=15, msg="healthz warm_pages")
            metrics = urllib.request.urlopen(
                f"http://127.0.0.1:{gw.port}/metrics",
                timeout=10).read().decode()
            assert 'replica_warm_pages_total{replica="r1"}' in metrics
            assert "warm_transfer_seconds" in metrics
            # first post-restart shared-prefix request: one terminal,
            # correct bytes, and a prefix hit on the warmed chain
            body = json.dumps({"prompt": CHAIN + [2],
                               "max_new_tokens": 4,
                               "stream": False}).encode()
            resp = urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{gw.port}/v1/generate", data=body,
                method="POST"), timeout=30)
            payload = json.loads(resp.read())
            oracle = FakeEngineWorker()
            assert payload["outcome"] == "ok"
            assert payload["token_ids"] == \
                oracle.expected_tokens(CHAIN + [2], 4)
            # the prefix hit shows on the request's access record
            wait_for(lambda: any(
                r["outcome"] == "ok" and r["prefix_hit"]
                for r in exporter.of_kind("access")),
                timeout=10, msg="prefix-hit access record")
            gw.metrics.check_conservation()
            assert health["replicas"]["r0"]["state"] == "up"
        finally:
            gw.stop_sync()
            sup.stop(drain=False)

    def test_no_live_peers_degrades_to_cold_rejoin(self):
        exporter = RecordingExporter()
        gw, sup = self._build(warm_rids=(), exporter=exporter)
        try:
            # kill BOTH children: whichever rejoins first has no live
            # peer to pull from and must still come up cold
            self._kill_child(sup, "r0")
            self._kill_child(sup, "r1")
            wait_for(lambda: all(
                st["state"] == "up" for st in sup.status().values()),
                timeout=30, msg="fleet healed")
            wait_for(lambda: len(exporter.of_kind("warmup")) >= 2,
                     timeout=30, msg="warmup events")
            statuses = {r["status"] for r in exporter.of_kind("warmup")}
            assert statuses <= {"cold"}
            # cold but SERVING: the fleet still answers correctly
            body = json.dumps({"prompt": [11, 7], "max_new_tokens": 5,
                               "stream": False}).encode()
            payload = json.loads(urllib.request.urlopen(
                urllib.request.Request(
                    f"http://127.0.0.1:{gw.port}/v1/generate",
                    data=body, method="POST"), timeout=30).read())
            oracle = FakeEngineWorker()
            assert payload["outcome"] == "ok"
            assert payload["token_ids"] == \
                oracle.expected_tokens([11, 7], 5)
            gw.metrics.check_conservation()
        finally:
            gw.stop_sync()
            sup.stop(drain=False)

    def test_conservation_through_randomized_kill9_with_warming(self):
        """The ISSUE drill: a seeded random kill -9 schedule interleaves
        restarts (each spawning a warm pull) with live traffic — every
        HTTP request still gets exactly one terminal and the gateway
        ledger balances."""
        exporter = RecordingExporter()
        gw, sup = self._build(exporter=exporter)
        rng = random.Random(20240806)
        stop_killing = threading.Event()
        kills = []

        def killer():
            while not stop_killing.is_set():
                time.sleep(rng.uniform(0.15, 0.4))
                with sup._lock:
                    up = [r for r in sup._replicas.values()
                          if r.state == "up" and r.proc is not None
                          and r.proc.poll() is None]
                if not up:
                    continue
                victim = rng.choice(up)
                victim.proc.kill()
                kills.append(victim.replica_id)

        outcomes = []

        def client(seed):
            crng = random.Random(seed)
            for _ in range(6):
                if crng.random() < 0.5:  # ride the warmed prefix chain
                    prompt = CHAIN + [crng.randrange(1, 50)]
                else:
                    prompt = [crng.randrange(1, 50)
                              for _ in range(crng.randrange(1, 5))]
                body = json.dumps({
                    "prompt": prompt,
                    "max_new_tokens": crng.randrange(4, 20),
                    "stream": False}).encode()
                req = urllib.request.Request(
                    f"http://127.0.0.1:{gw.port}/v1/generate",
                    data=body, method="POST")
                try:
                    resp = urllib.request.urlopen(req, timeout=30)
                    payload = json.loads(resp.read())
                except urllib.error.HTTPError as err:
                    payload = json.loads(err.read())
                outcomes.append(payload["outcome"])

        kt = threading.Thread(target=killer, daemon=True)
        kt.start()
        clients = [threading.Thread(target=client, args=(s,), daemon=True)
                   for s in range(4)]
        try:
            for t in clients:
                t.start()
            for t in clients:
                t.join(timeout=120)
                assert not t.is_alive(), "client wedged without terminal"
            stop_killing.set()
            kt.join(timeout=5)
            assert len(outcomes) == 24  # exactly one terminal each
            assert kills, "the schedule never actually killed a child"
            gw.metrics.check_conservation()
            wait_for(lambda: all(
                st["state"] == "up" for st in sup.status().values()),
                timeout=30, msg="fleet healed")
            # every restart attempted a warm rejoin (any status: a
            # concurrently-dying donor legitimately ends cold)
            wait_for(
                lambda: len(exporter.of_kind("warmup")) >= len(set(kills)),
                timeout=30, msg="warmup attempts recorded")
            for record in exporter.of_kind("warmup"):
                assert record["status"] in ("warmed", "partial", "cold")
        finally:
            stop_killing.set()
            gw.stop_sync()
            sup.stop(drain=False)
