"""bench.py hang-proof orchestration (parent side, no device work).

Round 2's benchmark produced nothing because the in-process run had no
wall-clock protection: a wedged backend init / kernel raises no
exception. These tests drive the parent orchestration against fake
children (CHILD_ARGV monkeypatched) covering every child outcome —
success, error, SIGINT-responsive hang, SIGINT-ignoring wedge — and
assert the driver contract: exactly one JSON line on stdout, always.
"""

from __future__ import annotations

import json
import os
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import bench  # noqa: E402

FAKE_CHILD = textwrap.dedent(
    """
    import json, os, signal, sys, time

    spec = json.loads(os.environ["FAKE_SPEC"])
    ab = os.environ.get("BENCH_MOE_AB") or None
    if os.environ.get("BENCH_PROBE") == "1":
        mode = "probe"
    elif os.environ.get("BENCH_CPU_FALLBACK") == "1":
        mode = "cpu_fallback"
    elif ab:
        mode = "moe_" + ab
    elif os.environ.get("BENCH_PREFLIGHT") == "1":
        mode = "preflight"
    elif os.environ.get("SCALETORCH_TPU_DISABLE_PALLAS") == "1":
        mode = "sdpa_row"
    else:
        mode = "pallas_row"
    # A/B legs / probe / cpu-fallback default to a fast ok so specs
    # written for the attention-path tests keep passing.
    if ab or mode in ("probe", "cpu_fallback"):
        beh = spec.get(mode, "ok")
    else:
        beh = spec[mode]

    def mark(stage):
        print(json.dumps({"event": "progress", "stage": stage}),
              file=sys.stderr, flush=True)

    if beh == "hang_at_init":          # dead tunnel: no marker ever
        time.sleep(600)
    mark("backend_up")
    if beh == "hang":                  # SIGINT-responsive mid-run hang
        time.sleep(600)
    if beh == "wedge":                 # ignores SIGINT (stuck in C++)
        signal.signal(signal.SIGINT, signal.SIG_IGN)
        time.sleep(600)
    if beh == "error":
        print(json.dumps({"metric": mode, "error": "boom"}))
        sys.exit(1)
    mark("done")
    if mode == "probe":
        print(json.dumps({
            "probe": "ok",
            "platform": spec.get("probe_platform", "tpu"),
            "device": "fake", "count": 1,
        }), flush=True)
        sys.exit(0)
    if mode == "cpu_fallback":
        print(json.dumps({
            "metric": "dense-tiny_seq512_cpu_fallback_tok_s",
            "value": 700.0, "unit": "tok/s (cpu)", "vs_baseline": 1.0,
            "cpu_fallback": True, "device": "cpu",
        }), flush=True)
        sys.exit(0)
    if ab:
        print(json.dumps({
            "metric": "moe_dispatch_" + ab,
            "step_time_s": spec.get(mode + "_step", 1.0),
            "tokens_per_second": 1000.0, "mfu": 10.0,
        }), flush=True)
        sys.exit(0)
    if mode == "preflight":
        print(json.dumps({"preflight": "ok", "step_ms": 1.0}))
    else:
        mfu = spec[mode + "_mfu"]
        print(json.dumps({
            "metric": "qwen3-0.6b_seq8192_bs1_gc_single_chip_mfu",
            "value": mfu, "unit": "% MFU", "vs_baseline": round(mfu / 39.0, 3),
            "tokens_per_second": 9000.0,
            "attention_path": "sdpa" if mode == "sdpa_row" else "pallas",
        }), flush=True)
    if beh == "ok_then_hang":           # result printed, teardown stalls
        time.sleep(600)
    if beh == "ok_then_wedge":          # result printed, teardown ignores SIGINT
        signal.signal(signal.SIGINT, signal.SIG_IGN)
        time.sleep(600)
    """
)


@pytest.fixture()
def fake_bench(tmp_path, monkeypatch):
    """Point bench at a scriptable fake child; run in a tmp cwd."""
    child = tmp_path / "fake_child.py"
    child.write_text(FAKE_CHILD)
    monkeypatch.setattr(bench, "CHILD_ARGV", [sys.executable, str(child)])
    monkeypatch.chdir(tmp_path)
    monkeypatch.setenv("BENCH_SIGINT_WAITS", "1,1")
    # These tests exercise the TPU orchestration against fake children;
    # the phase-0 CPU-fallback gate must stand down (the test env itself
    # runs JAX_PLATFORMS=cpu, which would otherwise short-circuit it).
    monkeypatch.setenv("BENCH_FORCE_CPU", "0")
    # 399: phase 1+2 fit (each check needs >=360/180 remaining) but the
    # phase-3 extra-rows loop (needs >=400) stays off unless a test
    # raises the budget explicitly
    monkeypatch.setenv("BENCH_TOTAL_BUDGET", "399")
    monkeypatch.setenv("BENCH_ROW_BUDGET", "10")
    monkeypatch.setenv("BENCH_PREFLIGHT_BUDGET", "5")
    monkeypatch.setenv("BENCH_PALLAS_ROW_BUDGET", "5")
    monkeypatch.setenv("BENCH_EXTRA_ROW_BUDGET", "10")
    monkeypatch.setenv("BENCH_MOE_AB_BUDGET", "10")

    def set_spec(**spec):
        monkeypatch.setenv("FAKE_SPEC", json.dumps(spec))

    return set_spec


def _stdout_line(capsys):
    out = [ln for ln in capsys.readouterr().out.splitlines() if ln.strip()]
    assert len(out) == 1, f"driver contract: exactly one stdout line, got {out}"
    return json.loads(out[0])


def test_pallas_wins_when_faster(fake_bench, capsys):
    fake_bench(sdpa_row="ok", sdpa_row_mfu=45.4,
               preflight="ok", pallas_row="ok", pallas_row_mfu=52.0)
    assert bench.run_headline() == 0
    line = _stdout_line(capsys)
    assert line["value"] == 52.0
    assert line["attention_path"] == "pallas"
    assert line["sdpa_mfu"] == 45.4
    table = json.loads(open("bench_table.json").read())
    assert set(table) == {bench.HEADLINE + "_sdpa", bench.HEADLINE + "_pallas"}


def test_sdpa_kept_when_pallas_slower(fake_bench, capsys):
    fake_bench(sdpa_row="ok", sdpa_row_mfu=45.4,
               preflight="ok", pallas_row="ok", pallas_row_mfu=40.0)
    assert bench.run_headline() == 0
    line = _stdout_line(capsys)
    assert line["value"] == 45.4
    assert line["attention_path"] == "sdpa"
    assert line["pallas_mfu"] == 40.0


@pytest.mark.slow
def test_preflight_wedge_still_reports_banked_row(fake_bench, capsys):
    """The round-2 failure shape: the Pallas path wedges ignoring SIGINT.
    The banked SDPA number must still be the stdout line."""
    fake_bench(sdpa_row="ok", sdpa_row_mfu=45.4, preflight="wedge")
    assert bench.run_headline() == 0
    line = _stdout_line(capsys)
    assert line["value"] == 45.4
    assert "budget" in line["pallas_skipped"]


@pytest.mark.slow
def test_pallas_row_hang_still_reports_banked_row(fake_bench, capsys):
    fake_bench(sdpa_row="ok", sdpa_row_mfu=45.4,
               preflight="ok", pallas_row="hang")
    assert bench.run_headline() == 0
    line = _stdout_line(capsys)
    assert line["value"] == 45.4
    assert "pallas_skipped" in line


@pytest.mark.slow
def test_result_kept_when_child_stalls_in_teardown(fake_bench, capsys):
    """A child that printed its measurement but stalled in PJRT-client
    teardown still counts: the number is real, only the exit was late."""
    fake_bench(sdpa_row="ok_then_hang", sdpa_row_mfu=45.4, preflight="wedge")
    assert bench.run_headline() == 0
    line = _stdout_line(capsys)
    assert line["value"] == 45.4
    assert line["late_exit"] is True


@pytest.mark.slow
def test_wedged_banked_child_skips_the_pallas_experiment(fake_bench, capsys):
    """A result-then-wedge child holds the chip: the banked number is
    reported but NO further device subprocess may be launched at it."""
    fake_bench(sdpa_row="ok_then_wedge", sdpa_row_mfu=45.4,
               preflight="ok", pallas_row="ok", pallas_row_mfu=99.0)
    assert bench.run_headline() == 0
    line = _stdout_line(capsys)
    assert line["value"] == 45.4  # the pallas row must never have run
    assert "chip held" in line["pallas_skipped"]


def test_dead_tunnel_fails_fast_with_classified_error(fake_bench, capsys,
                                                      monkeypatch):
    monkeypatch.setenv("BENCH_ROW_BUDGET", "2")
    fake_bench(sdpa_row="hang_at_init")
    assert bench.run_headline() == 1
    line = _stdout_line(capsys)
    assert line["metric"] == "error"
    assert line["vs_baseline"] == 0
    assert "tunnel" in line  # init-hang classified as dead tunnel


def test_child_error_propagates(fake_bench, capsys, monkeypatch):
    fake_bench(sdpa_row="error")
    assert bench.run_headline() == 1
    line = _stdout_line(capsys)
    assert line["metric"] == "error"
    assert "boom" in line["error"]


def test_mid_run_hang_budgets_and_classifies_stage(fake_bench, monkeypatch):
    monkeypatch.setenv("FAKE_SPEC", json.dumps({"sdpa_row": "hang"}))
    res = bench._run_child({"BENCH_ROW": bench.HEADLINE,
                            "SCALETORCH_TPU_DISABLE_PALLAS": "1"}, 2, "sdpa_row")
    assert res.timed_out and not res.wedged  # SIGINT worked
    assert res.stage == "backend_up"
    assert "backend_up" in res.error


@pytest.mark.slow
def test_table_mode_short_circuits_after_wedge(fake_bench, capsys, monkeypatch):
    """A wedged row must not burn every later row's budget: the chip is
    held, so remaining rows are recorded as skipped."""
    monkeypatch.setenv("BENCH_TABLE_ROW_BUDGET", "2")
    # every row uses the non-disable path in table mode -> pallas_row
    fake_bench(pallas_row="wedge")
    assert bench.run_table() == 1
    table = json.loads(open("bench_table.json").read())
    # every single-chip row + the two dispatch A/B legs, all accounted for
    assert len(table) == len(bench.SINGLE_CHIP_ROWS) + 2
    statuses = [v.get("error", "") for v in table.values()]
    assert any("budget" in s for s in statuses[:1])
    assert all("skipped: chip wedged" in s for s in statuses[1:])
    line = _stdout_line(capsys)
    assert line["metric"] == "error"


def test_extra_rows_fill_remaining_budget(fake_bench, capsys, monkeypatch):
    """Phase 3: with budget left after the headline decision, extra table
    rows are measured on the winning attention path and land in
    bench_table.json — one driver invocation banks table evidence."""
    monkeypatch.setenv("BENCH_TOTAL_BUDGET", "100000")
    fake_bench(sdpa_row="ok", sdpa_row_mfu=45.4,
               preflight="ok", pallas_row="ok", pallas_row_mfu=52.0)
    assert bench.run_headline() == 0
    line = _stdout_line(capsys)
    assert line["value"] == 52.0
    table = json.loads(open("bench_table.json").read())
    assert "qwen3-0.6b_seq16384_bs1_gc" in table  # the 56.0%-MFU target row
    assert line["rows_measured"] == len(table)


@pytest.mark.slow
def test_extra_rows_stop_after_a_timeout(fake_bench, capsys, monkeypatch):
    """A row that exceeds its budget ends phase 3 — the tail of the
    window must not be burned on a sick chip — and the headline line
    still prints."""
    monkeypatch.setenv("BENCH_TOTAL_BUDGET", "100000")
    # pallas experiment off -> extra rows run on the sdpa path, which
    # hangs for every row after the banked one ran fine... so make the
    # banked row ok and poison only the extras via a one-shot flag file
    fake_bench(sdpa_row="ok", sdpa_row_mfu=45.4, preflight="error",
               pallas_row="ok", pallas_row_mfu=52.0)
    # after the banked row, flip the spec so extra rows hang
    real_run_child = bench._run_child
    calls = []

    def spying(env, budget, label):
        if label not in ("sdpa_row", "pallas_preflight", "pallas_row"):
            import os as _os

            _os.environ["FAKE_SPEC"] = json.dumps({"sdpa_row": "hang"})
        calls.append(label)
        return real_run_child(env, budget, label)

    monkeypatch.setattr(bench, "_run_child", spying)
    assert bench.run_headline() == 0
    line = _stdout_line(capsys)
    assert line["value"] == 45.4
    # exactly one extra row attempted: it timed out and ended phase 3
    extras = [c for c in calls
              if c not in ("sdpa_row", "pallas_preflight", "pallas_row")]
    assert len(extras) == 1


@pytest.mark.slow
def test_save_attn_recipe_row_gated_on_pallas_win(fake_bench, capsys,
                                                  monkeypatch):
    """The bf16+save_attn seq-16384 recipe exists for the flash kernel's
    saved residuals: it must run when pallas wins and be skipped when
    SDPA wins (keeping the dispatch A/B reachable in-budget)."""
    monkeypatch.setenv("BENCH_TOTAL_BUDGET", "100000")
    fake_bench(sdpa_row="ok", sdpa_row_mfu=45.4,
               preflight="ok", pallas_row="ok", pallas_row_mfu=52.0)
    assert bench.run_headline() == 0
    _stdout_line(capsys)
    table = json.loads(open("bench_table.json").read())
    assert "qwen3-0.6b_seq16384_bf16_save_attn" in table

    fake_bench(sdpa_row="ok", sdpa_row_mfu=45.4, preflight="error")
    assert bench.run_headline() == 0
    _stdout_line(capsys)
    table = json.loads(open("bench_table.json").read())
    assert "qwen3-0.6b_seq16384_bs1_gc" in table
    assert "qwen3-0.6b_seq16384_bf16_save_attn" not in table


def test_moe_dispatch_ab_measured_after_seq16k(fake_bench, capsys,
                                               monkeypatch):
    """Phase 3.5: with budget, the einsum/index wall-clock A/B runs right
    after the priority seq-16384 row and the headline line carries the
    measured index speedup (the on-chip verdict on the 2.65x
    compiled-FLOPs claim)."""
    monkeypatch.setenv("BENCH_TOTAL_BUDGET", "100000")
    fake_bench(sdpa_row="ok", sdpa_row_mfu=45.4, preflight="error",
               moe_einsum="ok", moe_einsum_step=2.4,
               moe_index="ok", moe_index_step=1.2)
    assert bench.run_headline() == 0
    line = _stdout_line(capsys)
    assert line["moe_dispatch_index_speedup"] == 2.0
    table = json.loads(open("bench_table.json").read())
    assert table["moe_dispatch_ab"]["index_speedup_wallclock"] == 2.0
    # ordering: the A/B must come before the bulk table rows so a tight
    # window still settles the dispatch question
    labels = list(table)
    assert labels.index("moe_dispatch_einsum") < labels.index(
        "qwen3-0.6b_seq2048_bs2")
    assert labels.index("qwen3-0.6b_seq16384_bs1_gc") < labels.index(
        "moe_dispatch_einsum")


@pytest.mark.slow
def test_moe_dispatch_ab_error_leg_skips_ratio(fake_bench, capsys,
                                               monkeypatch):
    """A failed A/B leg must not fabricate a speedup; the remaining table
    rows still run."""
    monkeypatch.setenv("BENCH_TOTAL_BUDGET", "100000")
    fake_bench(sdpa_row="ok", sdpa_row_mfu=45.4, preflight="error",
               moe_einsum="error", moe_index="ok", moe_index_step=1.2)
    assert bench.run_headline() == 0
    line = _stdout_line(capsys)
    assert "moe_dispatch_index_speedup" not in line
    table = json.loads(open("bench_table.json").read())
    assert "moe_dispatch_ab" not in table
    assert "error" in table["moe_dispatch_einsum"]
    assert "qwen3-0.6b_seq2048_bs2" in table  # bulk rows still measured


def test_table_mode_appends_dispatch_ab(fake_bench, capsys, monkeypatch):
    """--table: the dispatch A/B legs run after the single-chip rows and
    the ratio summary lands in the table artifact."""
    monkeypatch.setenv("BENCH_TABLE_ROW_BUDGET", "10")
    fake_bench(sdpa_row="ok", sdpa_row_mfu=45.4,
               preflight="ok", pallas_row="ok", pallas_row_mfu=52.0,
               moe_einsum="ok", moe_einsum_step=3.0,
               moe_index="ok", moe_index_step=2.0)
    assert bench.run_table() == 0
    _stdout_line(capsys)  # driver contract: exactly one stdout line
    table = json.loads(open("bench_table.json").read())
    assert table["moe_dispatch_ab"]["index_speedup_wallclock"] == 1.5
    assert len(table) == len(bench.SINGLE_CHIP_ROWS) + 3  # 2 legs + summary


def test_stale_child_mode_env_cannot_hijack_children(fake_bench, capsys,
                                                     monkeypatch):
    """An exported BENCH_PREFLIGHT=1 left over from manual debugging must
    not turn every orchestration child into a preflight."""
    monkeypatch.setenv("BENCH_PREFLIGHT", "1")
    fake_bench(sdpa_row="ok", sdpa_row_mfu=45.4,
               preflight="ok", pallas_row="ok", pallas_row_mfu=52.0)
    assert bench.run_headline() == 0
    line = _stdout_line(capsys)
    assert line["value"] == 52.0  # real rows ran, not preflights


def test_last_stage_parser():
    err = "\n".join([
        "noise",
        json.dumps({"event": "progress", "stage": "backend_up"}),
        "WARNING: something",
        json.dumps({"event": "progress", "stage": "compiled"}),
    ])
    assert bench._last_stage(err) == "compiled"
    assert bench._last_stage("no markers here") is None


# ---------------------------------------------------------------------------
# Phase-0 CPU fallback (the r03-r05 un-wedger)
# ---------------------------------------------------------------------------
def test_dead_relay_skips_backend_init_and_falls_back(fake_bench, capsys,
                                                      monkeypatch):
    """A configured-but-unreachable axon relay must route straight to the
    CPU row — no device child may even attempt a backend init."""
    monkeypatch.setenv("BENCH_FORCE_CPU", "")
    monkeypatch.setenv("JAX_PLATFORMS", "")
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "127.0.0.1")  # nothing listens
    fake_bench(cpu_fallback="ok")  # a TPU row would KeyError the fake child
    assert bench.run_headline() == 0
    line = _stdout_line(capsys)
    assert line["cpu_fallback"] is True
    assert "relay" in line["cpu_fallback_reason"]
    assert "tok/s" in line["unit"]


def test_cpu_platform_env_falls_back_without_probe(fake_bench, capsys,
                                                   monkeypatch):
    monkeypatch.setenv("BENCH_FORCE_CPU", "")
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    fake_bench(cpu_fallback="ok")
    assert bench.run_headline() == 0
    line = _stdout_line(capsys)
    assert line["cpu_fallback"] is True
    assert "JAX_PLATFORMS" in line["cpu_fallback_reason"]


def test_probe_finding_cpu_platform_falls_back(fake_bench, capsys,
                                               monkeypatch):
    monkeypatch.setenv("BENCH_FORCE_CPU", "")
    monkeypatch.setenv("JAX_PLATFORMS", "")
    fake_bench(probe_platform="cpu", cpu_fallback="ok")
    assert bench.run_headline() == 0
    line = _stdout_line(capsys)
    assert line["cpu_fallback"] is True
    assert "not tpu" in line["cpu_fallback_reason"]


def test_probe_timeout_falls_back_within_budget(fake_bench, capsys,
                                                monkeypatch):
    """A probe child that hangs at backend init (the dead-tunnel
    signature) must burn only the probe budget, then go CPU."""
    monkeypatch.setenv("BENCH_FORCE_CPU", "")
    monkeypatch.setenv("JAX_PLATFORMS", "")
    monkeypatch.setenv("BENCH_PROBE_BUDGET", "2")
    fake_bench(probe="hang_at_init", cpu_fallback="ok")
    assert bench.run_headline() == 0
    line = _stdout_line(capsys)
    assert line["cpu_fallback"] is True
    assert "probe" in line["cpu_fallback_reason"]


def test_healthy_tpu_probe_proceeds_to_headline(fake_bench, capsys,
                                                monkeypatch):
    """With a live TPU behind the probe, the normal headline phases run
    and the stdout line is the banked MFU row, not the CPU fallback."""
    monkeypatch.setenv("BENCH_FORCE_CPU", "")
    monkeypatch.setenv("JAX_PLATFORMS", "")
    fake_bench(probe_platform="tpu", sdpa_row="ok", sdpa_row_mfu=45.4,
               preflight="ok", pallas_row="ok", pallas_row_mfu=52.0)
    assert bench.run_headline() == 0
    line = _stdout_line(capsys)
    assert "cpu_fallback" not in line
    assert line["value"] == 52.0


def test_failed_cpu_fallback_still_prints_one_error_line(fake_bench, capsys,
                                                         monkeypatch):
    monkeypatch.setenv("BENCH_FORCE_CPU", "1")
    fake_bench(cpu_fallback="error")
    assert bench.run_headline() == 1
    line = _stdout_line(capsys)
    assert line["metric"] == "error"
    assert line["cpu_fallback_attempted"] is True
