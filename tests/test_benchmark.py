"""Benchmark library + presets (scaletorch_tpu/benchmark.py).

The reference's sweep correctness is untested; here the in-process
runner used by bench.py and scripts/benchmark_comprehensive.py is
exercised on the virtual 8-device mesh.
"""

from __future__ import annotations

import pytest

from scaletorch_tpu.benchmark import benchmark_config, make_bench_args
from scaletorch_tpu.models.presets import MODEL_PRESETS, preset


def test_presets_known_architectures():
    p = preset("qwen3-0.6b")
    assert p["hidden_size"] == 1024 and p["num_hidden_layers"] == 28
    moe = preset("qwen3-30b-a3b")
    assert moe["num_experts"] == 128 and moe["num_experts_per_tok"] == 8
    with pytest.raises(KeyError, match="unknown model preset"):
        preset("nope")
    # preset() hands out copies — mutating one must not poison the table
    p["hidden_size"] = 1
    assert preset("qwen3-0.6b")["hidden_size"] == 1024


def test_make_bench_args_shapes():
    cfg = make_bench_args("qwen3-0.6b", seq=4096, micro_bs=2, gc=True, tp=1)
    assert cfg.sequence_length == 4096
    assert cfg.micro_batch_size == 2
    assert cfg.gradient_checkpointing is True
    assert cfg.synthetic_data is True


@pytest.mark.parametrize("name", sorted(MODEL_PRESETS))
def test_all_presets_build_valid_configs(name):
    make_bench_args(name, seq=256)


@pytest.mark.slow
def test_benchmark_config_runs_on_mesh(devices8):
    cfg = make_bench_args(
        "dense-tiny", seq=128, dp=8, micro_bs=1, dtype="float32",
    )
    r = benchmark_config(cfg, warmup=1, steps=2)
    assert r["num_chips"] == 8
    assert r["tokens_per_second"] > 0
    assert r["loss"] == pytest.approx(8.3, abs=0.5)  # ~ln(4096) at init
    assert r["mfu"] > 0
