"""Config validation — parity with reference config.py __post_init__ checks."""

import pytest

from scaletorch_tpu.config import (
    ParallelArguments,
    ScaleTorchTPUArguments,
    parse_args,
)


class TestParallelArguments:
    def test_defaults_ok(self):
        pa = ParallelArguments()
        # afab by measurement (tools/pp_schedule_compare.py): 1F1B-equal
        # bubble at lower cost in the SPMD design; '1f1b' stays accepted
        # for reference CLI parity.
        assert pa.pp_engine == "afab"

    def test_bad_dim(self):
        with pytest.raises(ValueError, match=">= 1"):
            ParallelArguments(tensor_parallel_size=0)

    def test_bad_engine(self):
        with pytest.raises(ValueError, match="pp_engine"):
            ParallelArguments(pp_engine="gpipe")

    def test_1f1b_alias_warns_and_rewrites(self):
        """VERDICT r3 weak #3: the chunked schedule is 1F1B's MEMORY bound,
        not its schedule; reference-config porters must hear about the
        measured ~1.22x slowdown instead of getting it silently."""
        with pytest.warns(RuntimeWarning, match="SLOWER than 'afab'"):
            pa = ParallelArguments(pp_engine="1f1b",
                                   pipeline_parallel_size=2)
        assert pa.pp_engine == "memory_chunked"

    def test_1f1b_alias_silent_without_pp(self):
        import warnings

        with warnings.catch_warnings():
            warnings.simplefilter("error")
            pa = ParallelArguments(pp_engine="1f1b")  # pp=1: no regression
        assert pa.pp_engine == "memory_chunked"

    def test_memory_chunked_accepted_quietly(self):
        import warnings

        with warnings.catch_warnings():
            warnings.simplefilter("error")
            pa = ParallelArguments(pp_engine="memory_chunked",
                                   pipeline_parallel_size=2)
        assert pa.pp_engine == "memory_chunked"

    def test_sp_requires_tp(self):
        with pytest.raises(ValueError, match="sequence_parallel"):
            ParallelArguments(sequence_parallel=True, tensor_parallel_size=1)


class TestInterleavedCliKnobs:
    def test_cli_flags_reach_model_config(self):
        from scaletorch_tpu.config import parse_args
        from scaletorch_tpu.trainer.trainer import build_model_config

        cfg = parse_args([
            "--model_type", "qwen3_moe", "--num_hidden_layers", "4",
            "--hidden_size", "32", "--num_attention_heads", "4",
            "--vocab_size", "64", "--mlp_only_layers", "2",
            "--decoder_sparse_step", "2",
        ])
        mc = build_model_config(cfg)
        assert mc.sparse_layer_ids() == (1, 3)
        assert mc.dense_layer_ids() == (0, 2)

    def test_defaults_leave_architecture_uniform(self):
        from scaletorch_tpu.config import parse_args
        from scaletorch_tpu.trainer.trainer import build_model_config

        cfg = parse_args([
            "--model_type", "qwen3_moe", "--num_hidden_layers", "2",
            "--hidden_size", "32", "--num_attention_heads", "4",
            "--vocab_size", "64",
        ])
        assert build_model_config(cfg).is_uniform_sparse

    def test_explicit_overrides_beat_hf_checkpoint(self, tmp_path):
        """--decoder_sparse_step 1 / --mlp_only_layers -1 must force an
        interleaved HF checkpoint back to uniform-sparse (e.g. to
        re-enable PP); omitted knobs keep the checkpoint's value."""
        transformers = pytest.importorskip("transformers")
        from scaletorch_tpu.config import parse_args
        from scaletorch_tpu.trainer.trainer import build_model_config

        hf = transformers.Qwen3MoeConfig(
            vocab_size=64, hidden_size=32, intermediate_size=64,
            moe_intermediate_size=48, num_hidden_layers=4,
            num_attention_heads=4, num_key_value_heads=2, head_dim=16,
            num_experts=4, num_experts_per_tok=2,
            mlp_only_layers=[2], decoder_sparse_step=2,
        )
        hf.save_pretrained(str(tmp_path))
        base = ["--model_type", "qwen3_moe",
                "--model_name_or_path", str(tmp_path)]
        # omitted -> checkpoint architecture kept
        mc = build_model_config(parse_args(base))
        assert mc.sparse_layer_ids() == (1, 3)
        # explicit values (including the defaults 1 / empty) override
        mc = build_model_config(parse_args(
            base + ["--decoder_sparse_step", "1",
                    "--mlp_only_layers", "-1"]))
        assert mc.is_uniform_sparse


class TestComposedArguments:
    def test_seq_divisible_by_cp(self):
        with pytest.raises(ValueError, match="not divisible"):
            ScaleTorchTPUArguments(sequence_length=1023, context_parallel_size=2)

    def test_global_batch_size_autofill(self):
        cfg = ScaleTorchTPUArguments(
            data_parallel_size=2,
            micro_batch_size=3,
            gradient_accumulation_steps=4,
        )
        assert cfg.global_batch_size == 24

    def test_global_batch_size_mismatch(self):
        with pytest.raises(ValueError, match="global_batch_size"):
            ScaleTorchTPUArguments(
                data_parallel_size=2, micro_batch_size=2, global_batch_size=5
            )

    def test_world_size(self):
        cfg = ScaleTorchTPUArguments(
            data_parallel_size=2,
            tensor_parallel_size=2,
            context_parallel_size=2,
        )
        assert cfg.world_size == 8
        cfg.validate_world_size(8)
        with pytest.raises(ValueError, match="device count"):
            cfg.validate_world_size(4)

    def test_num_microbatches_default(self):
        cfg = ScaleTorchTPUArguments(gradient_accumulation_steps=7)
        assert cfg.num_microbatches == 7

    def test_mesh_kwargs(self):
        cfg = ScaleTorchTPUArguments(tensor_parallel_size=4, data_parallel_size=2)
        assert cfg.mesh_kwargs() == dict(dp=2, pp=1, cp=1, ep=1, tp=4)


class TestCliParsing:
    def test_parse_args_roundtrip(self):
        cfg = parse_args(
            [
                "--tensor_parallel_size", "2",
                "--data_parallel_size", "4",
                "--sequence_length", "2048",
                "--learning_rate", "1e-3",
                "--pp_engine", "afab",
            ]
        )
        assert cfg.tensor_parallel_size == 2
        assert cfg.world_size == 8
        assert cfg.learning_rate == 1e-3
        assert cfg.pp_engine == "afab"
