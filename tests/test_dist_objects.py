"""Object collectives — single-process contracts.

The real multi-process path is attested by the 2-process cluster test
(tests/parallel/test_multihost.py, slow tier); here are the P==1
invariants every helper must keep (reference object_ops.py ones: torch
gather_object degenerates to identity at world_size 1).
"""


from scaletorch_tpu.dist import (
    all_gather_object,
    broadcast_object_list,
    collect_results,
    gather_object,
)


class TestSingleProcess:
    def test_all_gather_identity(self):
        obj = {"a": [1, 2], "b": ("x", None)}
        assert all_gather_object(obj) == [obj]

    def test_gather_rooted(self):
        assert gather_object(5, dst=0) == [5]

    def test_broadcast_in_place(self):
        objs = [1, {"k": 2}]
        out = broadcast_object_list(objs, src=0)
        assert out == [1, {"k": 2}]

    def test_collect_results_truncates(self):
        assert collect_results(["a", "b", "c"], size=2) == ["a", "b"]

    def test_collect_results_device_arg_accepted(self):
        # reference API parity: device='cpu'|'gpu'|'npu' accepted
        assert collect_results([1], size=1, device="npu") == [1]


def test_round_robin_interleaving_shape():
    """The merge order contract, exercised via the internal path the
    multi-process branch uses (parts -> interleave -> truncate)."""
    from scaletorch_tpu import dist as d

    parts = [["r0s0", "r0s1"], ["r1s0"]]
    interleaved = []
    longest = max(len(p) for p in parts)
    for j in range(longest):
        for p in parts:
            if j < len(p):
                interleaved.append(p[j])
    assert interleaved == ["r0s0", "r1s0", "r0s1"]
    # and the serializer round-trips arbitrary picklables
    buf = d._obj_to_u8({"x": (1, b"bytes")})
    assert d._u8_to_obj(buf, buf.size) == {"x": (1, b"bytes")}
