"""Elastic training fleet (resilience_distributed.ElasticCoordinator):
survive host loss by remeshing, not restarting.

The acceptance surface of the elastic layer, exercised hermetically in
one process. The end-to-end drills run the REAL ``Trainer.train``
remesh-and-resume outer loop / ``CoordinatedResilience`` /
``CheckpointManager`` on N simulated host threads over the REAL
``FileBus`` (deadline-bounded file collectives — the same transport
production uses for post-remesh epochs) and the shared
``FileMembershipStore``.

Covered here:
  * kill drill (``--ft_kill_host_at_step`` / ``--ft_kill_host``): host 2
    hard-killed after step 3 -> survivors detect the loss via the
    bounded collective deadline, agree a shrink epoch, restore from the
    latest checkpoint, continue to the absolute ``total_train_steps``
    target; a relaunched replacement parks at the rejoin barrier and is
    readmitted at the next checkpoint boundary — final params BITWISE
    equal to an undisturbed run;
  * hang drill (``--ft_host_hang_elastic``): a live-but-wedged host is
    evicted, wakes to find the fleet moved on, parks, and aborts loudly
    (ElasticRemeshError) when no grow boundary admits it;
  * membership transitions attested in JSONL telemetry (``membership``
    kind) + counters;
  * the epoch state machine unit-by-unit: suspect-round agreement,
    write-once epoch records, min-hosts floor, spurious-loss remesh in
    place, eviction -> park -> rejoin, grow via the epoch bus;
  * FileMembershipStore / FileBus / MembershipView primitives;
  * ``elastic_mesh_kwargs``: dp absorbs the host change, un-shrinkable
    geometries refuse loudly;
  * dp4 -> dp2 -> dp4 checkpoint round-trip pinning bitwise param /
    opt-state equality across ``load_latest(target_mesh=...)``;
  * ``remap_loader_position``: never double-counts, never skips a batch
    on a divisor shrink, composes with rollback skew;
  * the parse-time rejection matrix for ``--elastic``.
"""

import os
import threading
import time
from functools import partial

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from scaletorch_tpu.config import ScaleTorchTPUArguments
from scaletorch_tpu.data.dataloader import (
    MicroBatchDataLoader,
    SyntheticDataLoader,
    remap_loader_position,
)
from scaletorch_tpu.parallel.mesh import (
    MeshManager,
    MeshShrinkError,
    elastic_mesh_kwargs,
)
from scaletorch_tpu.resilience import FaultInjector, HostKilledError
from scaletorch_tpu.resilience_distributed import (
    CoordinatedResilience,
    DecisionBus,
    ElasticCoordinator,
    ElasticRemeshError,
    FileBus,
    FileMembershipStore,
    MembershipView,
    PeerLostError,
    _elastic_wrap,
    elastic_decision_bus,
)
from scaletorch_tpu.telemetry.export import (
    KNOWN_KINDS,
    TelemetryExporter,
    read_jsonl,
)
from tests.test_resilience import ToyTrainer, e2e_cfg, e2e_tokens


# ---------------------------------------------------------------------------
# Helpers
# ---------------------------------------------------------------------------


def wait_until(pred, timeout=30.0, poll=0.01, what="condition"):
    deadline = time.monotonic() + timeout
    while not pred():
        assert time.monotonic() < deadline, f"timed out waiting for {what}"
        time.sleep(poll)


def run_threads(fns, timeout=120.0):
    """Run ``{name: fn}`` on daemon threads; returns (results, errors)
    dicts. Catches BaseException: ``HostKilledError`` deliberately is
    NOT an Exception and must still be recorded, not dumped to stderr."""
    results, errors = {}, {}

    def worker(name, fn):
        try:
            results[name] = fn()
        except BaseException as exc:  # noqa: BLE001 — surfaced via errors
            errors[name] = exc

    threads = [threading.Thread(target=worker, args=(n, f), daemon=True)
               for n, f in fns.items()]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout)
    assert not any(t.is_alive() for t in threads), \
        "a simulated host wedged (elastic protocol desync?)"
    return results, errors


def file_bus_factory(store, deadline):
    """The production transport (FileBus over the membership directory),
    with a test-sized deadline."""

    def factory(view, rank):
        fb = FileBus(
            os.path.join(store.directory, "collective"),
            epoch=view.epoch, members=view.members, rank=rank,
            deadline=deadline,
        )
        return DecisionBus(
            num_processes=view.num_hosts,
            process_index=view.bus_index(rank),
            all_gather=fb.all_gather,
            broadcast=fb.broadcast,
        )

    return factory


def assert_trees_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def membership_events(path):
    return [e for e in read_jsonl(path) if e.get("kind") == "membership"]


def transitions(path):
    """Non-steady transitions (the founding 'steady' event is emitted
    only by ranks that raced to write the founding record first)."""
    return [e["transition"] for e in membership_events(path)
            if e["transition"] != "steady"]


def _raise_killed():
    raise HostKilledError("injected host kill")


def _reference_params(tmp_path, **kw):
    """An undisturbed single-trainer run — the bitwise oracle the
    elastic fleet must reproduce."""
    cfg = e2e_cfg(tmp_path / "ref", **kw)
    t = ToyTrainer(cfg, e2e_tokens())
    t.train()
    t.close()
    return t.params


# ---------------------------------------------------------------------------
# End-to-end drills: the REAL Trainer.train remesh-and-resume loop
# ---------------------------------------------------------------------------


@pytest.mark.multihost
class TestElasticDrills:
    FLEET = 4
    DEADLINE = 2.0

    def _fleet_kw(self, **extra):
        kw = dict(
            total_train_steps=5, resume="auto", elastic=True,
            elastic_deadline_seconds=self.DEADLINE,
            elastic_heartbeat_seconds=0.2,
        )
        kw.update(extra)
        return kw

    def _make_host(self, i, tmp_path, ckpt_dir, exporter_name, **cfg_kw):
        cfg = e2e_cfg(ckpt_dir, **self._fleet_kw(**cfg_kw))
        t = ToyTrainer(cfg, e2e_tokens())
        inj = t.resilience.injector
        inj.host_index = i
        inj.deliver_kill = _raise_killed
        exporter = TelemetryExporter(
            str(tmp_path / "telem" / f"{exporter_name}.jsonl"),
            process_index=i)
        t.elastic = ElasticCoordinator.from_config(
            cfg, rank=i, num_hosts=self.FLEET, exporter=exporter,
            store=FileMembershipStore(str(tmp_path / "membership")))
        t._test_exporter = exporter
        return t

    def test_kill_drill_shrink_restore_regrow_bitwise(self, tmp_path):
        """Host 2 killed after step 3: survivors shrink (epoch 1),
        restore the step-2 checkpoint, continue; a relaunched rank 2
        parks and is readmitted at the step-4 checkpoint boundary
        (epoch 2); every finisher's params are bitwise equal to an
        undisturbed run's, and the full epoch sequence is attested in
        membership JSONL + counters."""

        def fleet_host(i):
            t = self._make_host(
                i, tmp_path, tmp_path / f"host{i}",
                exporter_name=f"host{i}",
                ft_kill_host_at_step=3, ft_kill_host=2)
            t.coordinator = CoordinatedResilience(
                t.resilience, bus=t.elastic.bus)
            t.train()
            t.close()
            t._test_exporter.close()
            return t

        def relaunched_host():
            # a real launcher (scripts/launch_multihost.sh ELASTIC=1)
            # relaunches ONLY the dead rank after its crash-family exit;
            # polling the store for the shrink epoch stands in for that
            # process-scheduling delay
            store = FileMembershipStore(str(tmp_path / "membership"))
            wait_until(
                lambda: (store.latest_epoch() or {}).get("epoch", -1) >= 1,
                timeout=60.0, what="the shrink epoch record")
            # the coordinator must exist (parked) BEFORE the rejoin
            # request: a grow that fires mid-construction is then
            # handled by join()'s poll instead of racing the view
            cfg = e2e_cfg(tmp_path / "host0",
                          **self._fleet_kw(save_frequency=0))
            exporter = TelemetryExporter(
                str(tmp_path / "telem" / "host2b.jsonl"), process_index=2)
            coord = ElasticCoordinator.from_config(
                cfg, rank=2, num_hosts=self.FLEET, exporter=exporter,
                store=store)
            assert coord.parked and coord.needs_join
            store.request_rejoin(2)
            t = ToyTrainer(cfg, e2e_tokens())
            t.resilience.injector.host_index = 2
            t.elastic = coord
            t.coordinator = CoordinatedResilience(t.resilience)
            t.train()
            t.close()
            exporter.close()
            return t

        fns = {i: partial(fleet_host, i) for i in range(self.FLEET)}
        fns["2b"] = relaunched_host
        results, errors = run_threads(fns)

        # the killed host unwound on the BaseException kill — nothing
        # between the injection site and the thread top caught it
        assert isinstance(errors.pop(2), HostKilledError)
        assert errors == {}

        expected = _reference_params(tmp_path, total_train_steps=5)
        final_view = MembershipView(2, (0, 1, 2, 3))
        for name in (0, 1, 3, "2b"):
            t = results[name]
            assert t.global_step == 5
            assert t.elastic.view == final_view
            assert t.loader.position == 5 and t._loader_skew == 0
            assert_trees_equal(t.params, expected)

        # counters: one loss event -> one suspect round -> one shrink,
        # then one grow readmitting the relaunched rank
        c0 = results[0].elastic.counters()
        assert c0["elastic_peer_loss_events"] == 1
        assert c0["elastic_suspect_rounds"] == 1
        assert c0["elastic_shrinks"] == 1 and c0["elastic_grows"] == 1
        assert c0["elastic_hosts_lost"] == 1
        assert c0["elastic_hosts_rejoined"] == 1
        assert c0["elastic_epochs_adopted"] == 2
        assert c0["elastic_evictions"] == 0
        cb = results["2b"].elastic.counters()
        assert cb["elastic_epochs_adopted"] == 1
        assert cb["elastic_hosts_rejoined"] == 1
        assert cb["elastic_evictions"] == 0

        # membership JSONL: the full epoch sequence, per rank
        for i in (0, 1, 3):
            events = membership_events(
                tmp_path / "telem" / f"host{i}.jsonl")
            assert transitions(
                tmp_path / "telem" / f"host{i}.jsonl"
            ) == ["suspect", "shrink", "grow"]
            by = {e["transition"]: e for e in events}
            assert by["shrink"]["epoch"] == 1
            assert by["shrink"]["members"] == [0, 1, 3]
            assert by["shrink"]["lost"] == [2]
            assert by["grow"]["epoch"] == 2
            assert by["grow"]["members"] == [0, 1, 2, 3]
            assert by["grow"]["joined"] == [2]
            for e in events:
                assert e["kind"] == "membership" and e["rank"] == i
                assert e["num_hosts"] == len(e["members"])
        assert transitions(tmp_path / "telem" / "host2b.jsonl") == ["join"]
        (join_ev,) = [e for e in membership_events(
            tmp_path / "telem" / "host2b.jsonl")
            if e["transition"] == "join"]
        assert join_ev["epoch"] == 2 and join_ev["joined"] == [2]

        # store surfaces: epoch chain on disk, mailbox drained,
        # operator-visible heartbeats refreshed
        store = FileMembershipStore(str(tmp_path / "membership"))
        assert [store.epoch(n)["reason"] for n in (0, 1, 2)] \
            == ["found", "shrink", "grow"]
        assert store.pending_rejoins() == []
        assert os.path.exists(
            os.path.join(store.directory, "heartbeat_r0.json"))

    def test_hang_drill_evicts_wedged_host(self, tmp_path):
        """Host 2 stalls past the elastic deadline: the fleet evicts it
        and continues to the target bitwise-identically; the wedged host
        wakes, finds the epoch moved on, parks, and aborts loudly when
        no grow boundary ever admits it."""

        def host(i):
            # the hang must outlast loss detection (one deadline) PLUS
            # the survivors' alive round (another deadline), or the
            # wedged host answers the roll call and stays a member
            t = self._make_host(
                i, tmp_path, tmp_path / f"host{i}",
                exporter_name=f"host{i}",
                ft_host_hang_elastic=3, ft_kill_host=2,
                ft_host_hang_seconds=2 * self.DEADLINE + 1.5)
            if i == 2:
                # nobody relaunches anything in this drill: the parked
                # host must give up in bounded time, not block the test
                t.elastic.join_timeout = 3.0
            t.coordinator = CoordinatedResilience(
                t.resilience, bus=t.elastic.bus)
            t.train()
            t.close()
            t._test_exporter.close()
            return t

        results, errors = run_threads(
            {i: partial(host, i) for i in range(self.FLEET)})

        err = errors.pop(2)
        assert isinstance(err, ElasticRemeshError)
        assert "rejoin barrier" in str(err)
        assert errors == {}

        expected = _reference_params(tmp_path, total_train_steps=5)
        for i in (0, 1, 3):
            t = results[i]
            assert t.global_step == 5
            assert t.elastic.view == MembershipView(1, (0, 1, 3))
            assert_trees_equal(t.params, expected)
            assert transitions(
                tmp_path / "telem" / f"host{i}.jsonl"
            ) == ["suspect", "shrink"]
            assert t.elastic.counters()["elastic_hosts_lost"] == 1


# ---------------------------------------------------------------------------
# ElasticCoordinator state machine (store-level, no trainer)
# ---------------------------------------------------------------------------


class TestElasticCoordinator:
    def _coord(self, store, rank, *, num_hosts=3, deadline=0.4, **kw):
        return ElasticCoordinator(
            rank=rank, num_hosts=num_hosts, store=store,
            bus_factory=file_bus_factory(store, deadline),
            deadline_seconds=deadline, **kw)

    def test_founding_epoch_and_view(self, tmp_path):
        store = FileMembershipStore(str(tmp_path))
        c = self._coord(store, 0)
        assert c.view == MembershipView(0, (0, 1, 2))
        assert c.state == "steady" and not c.needs_join
        assert store.epoch(0)["reason"] == "found"
        # a later construction adopts the record instead of re-founding
        c2 = self._coord(store, 1)
        assert c2.view == c.view and c2.state == "steady"

    def test_relaunched_excluded_rank_parks(self, tmp_path):
        store = FileMembershipStore(str(tmp_path))
        store.propose_epoch({"epoch": 0, "members": [0, 1, 2],
                             "reason": "found", "step": None})
        store.propose_epoch({"epoch": 1, "members": [0, 1],
                             "reason": "shrink", "step": 3})
        c = self._coord(store, 2)
        assert c.parked and c.needs_join
        assert c.view == MembershipView(1, (0, 1))

    def test_suspect_round_agrees_shrink_epoch(self, tmp_path):
        store = FileMembershipStore(str(tmp_path))
        coords = {r: self._coord(store, r) for r in (0, 1)}  # rank 2 dead
        results, errors = run_threads(
            {r: partial(c.on_peer_lost, 5) for r, c in coords.items()},
            timeout=30.0)
        assert errors == {}
        assert results[0] == results[1] == MembershipView(1, (0, 1))
        for c in coords.values():
            cc = c.counters()
            assert cc["elastic_suspect_rounds"] == 1
            assert cc["elastic_shrinks"] == 1
            assert cc["elastic_hosts_lost"] == 1
        assert store.epoch(1)["step"] == 5

    def test_spurious_loss_remeshes_in_place(self, tmp_path):
        # every member answers the suspect round: same member set, new
        # epoch — the fleet re-synchronises without shedding anyone
        store = FileMembershipStore(str(tmp_path))
        coords = {r: self._coord(store, r) for r in range(3)}
        results, errors = run_threads(
            {r: partial(c.on_peer_lost, 7) for r, c in coords.items()},
            timeout=30.0)
        assert errors == {}
        assert all(v == MembershipView(1, (0, 1, 2))
                   for v in results.values())
        assert coords[0].counters()["elastic_hosts_lost"] == 0

    def test_min_hosts_floor_aborts_to_fleet_restart(self, tmp_path):
        store = FileMembershipStore(str(tmp_path))
        coords = {r: self._coord(store, r, min_hosts=3) for r in (0, 1)}
        _, errors = run_threads(
            {r: partial(c.on_peer_lost, 5) for r, c in coords.items()},
            timeout=30.0)
        assert all(isinstance(e, ElasticRemeshError)
                   for e in errors.values()) and len(errors) == 2
        assert all("elastic_min_hosts" in str(e) for e in errors.values())

    def test_evicted_host_parks_then_rejoins(self, tmp_path):
        store = FileMembershipStore(str(tmp_path))
        store.propose_epoch({"epoch": 0, "members": [0, 1, 2],
                             "reason": "found", "step": None})
        c2 = self._coord(store, 2)
        assert c2.state == "steady"
        # the fleet moved on without rank 2 (it hung past the deadline)
        store.propose_epoch({"epoch": 1, "members": [0, 1],
                             "reason": "shrink", "step": 9})
        out = {}
        th = threading.Thread(
            target=lambda: out.update(view=c2.on_peer_lost(9)),
            daemon=True)
        th.start()
        wait_until(lambda: store.pending_rejoins() == [2],
                   what="the rejoin request")
        store.propose_epoch({"epoch": 2, "members": [0, 1, 2],
                             "reason": "grow", "step": 10})
        th.join(10.0)
        assert not th.is_alive()
        assert out["view"] == MembershipView(2, (0, 1, 2))
        assert c2.pending_bootstrap and c2.needs_join
        assert c2.counters()["elastic_evictions"] == 1

    def test_join_timeout_is_loud(self, tmp_path):
        store = FileMembershipStore(str(tmp_path))
        store.propose_epoch({"epoch": 0, "members": [0],
                             "reason": "found", "step": None})
        c = self._coord(store, 1, num_hosts=2, join_timeout=0.3)
        assert c.parked
        with pytest.raises(ElasticRemeshError, match="rejoin barrier"):
            c.join(step=1)

    def test_maybe_grow_admits_parked_rank(self, tmp_path):
        store = FileMembershipStore(str(tmp_path))
        store.propose_epoch({"epoch": 0, "members": [0, 1],
                             "reason": "found", "step": None})
        store.propose_epoch({"epoch": 1, "members": [0],
                             "reason": "shrink", "step": 3})
        c0 = self._coord(store, 0, num_hosts=2, deadline=5.0)
        c1 = self._coord(store, 1, num_hosts=2, deadline=5.0)
        assert c0.view.members == (0,) and c1.parked
        assert c0.maybe_grow(step=4) is None  # empty mailbox: no-op
        out = {}
        th = threading.Thread(
            target=lambda: out.update(view=c1.join(step=4)), daemon=True)
        th.start()
        wait_until(lambda: store.pending_rejoins() == [1],
                   what="the rejoin request")
        view = c0.maybe_grow(step=4)
        th.join(10.0)
        assert not th.is_alive()
        assert view == out["view"] == MembershipView(2, (0, 1))
        assert store.pending_rejoins() == []  # mailbox drained
        assert c0.counters()["elastic_grows"] == 1
        assert c1.counters()["elastic_hosts_rejoined"] == 1
        assert c1.pending_bootstrap

    def test_beat_writes_heartbeat(self, tmp_path):
        store = FileMembershipStore(str(tmp_path))
        c = self._coord(store, 0, heartbeat_seconds=0.01)
        c.beat(step=7)
        import json

        with open(os.path.join(store.directory, "heartbeat_r0.json")) as f:
            hb = json.load(f)
        assert hb["rank"] == 0 and hb["step"] == 7 and hb["epoch"] == 0


# ---------------------------------------------------------------------------
# Primitives: store, bus, view, wrap
# ---------------------------------------------------------------------------


class TestMembershipPrimitives:
    def test_epoch_records_are_write_once(self, tmp_path):
        store = FileMembershipStore(str(tmp_path))
        assert store.propose_epoch(
            {"epoch": 1, "members": [0, 1], "reason": "shrink", "step": 3})
        assert not store.propose_epoch(
            {"epoch": 1, "members": [9], "reason": "shrink", "step": 3})
        assert store.epoch(1)["members"] == [0, 1]  # first writer won
        store.propose_epoch(
            {"epoch": 2, "members": [0], "reason": "shrink", "step": 4})
        assert store.latest_epoch()["epoch"] == 2

    def test_alive_and_rejoin_surfaces(self, tmp_path):
        store = FileMembershipStore(str(tmp_path))
        store.post_alive(3, 0, step=5)
        store.post_alive(3, 2, step=5)
        store.post_alive(4, 1, step=9)  # different epoch: not counted
        assert store.alive_set(3) == {0, 2}
        store.request_rejoin(7)
        store.request_rejoin(4)
        assert store.pending_rejoins() == [4, 7]
        store.clear_rejoin(4)
        store.clear_rejoin(4)  # idempotent
        assert store.pending_rejoins() == [7]

    def test_file_bus_gathers_in_member_order(self, tmp_path):
        fbs = {r: FileBus(str(tmp_path), epoch=0, members=(1, 3), rank=r,
                          deadline=5.0) for r in (1, 3)}
        results, errors = run_threads({
            r: partial(fb.all_gather, f"v{r}") for r, fb in fbs.items()})
        assert errors == {}
        assert results[1] == results[3] == ["v1", "v3"]
        # broadcast src indexes the MEMBERS tuple, not global ranks
        results, errors = run_threads({
            r: partial(fb.broadcast, [f"payload{r}"])
            for r, fb in fbs.items()})
        assert errors == {}
        assert results[1] == results[3] == ["payload1"]

    def test_file_bus_names_the_missing_rank(self, tmp_path):
        fb = FileBus(str(tmp_path), epoch=2, members=(0, 5), rank=0,
                     deadline=0.2)
        with pytest.raises(PeerLostError) as ei:
            fb.all_gather("x")
        assert ei.value.missing == (5,)
        assert "5" in str(ei.value)

    def test_membership_view_renumbers_ranks(self):
        view = MembershipView(3, (0, 2, 5))
        assert view.num_hosts == 3
        assert [view.bus_index(r) for r in (0, 2, 5)] == [0, 1, 2]
        bus = elastic_decision_bus(
            view, 5, DecisionBus(
                num_processes=3, process_index=2,
                all_gather=lambda obj: [obj] * 3,
                broadcast=lambda objs: objs))
        assert bus.process_index == 2 and not bus.is_main
        assert elastic_decision_bus(
            view, 0, DecisionBus(
                num_processes=3, process_index=0,
                all_gather=lambda obj: [obj] * 3,
                broadcast=lambda objs: objs)).is_main

    def test_elastic_wrap_normalises_transport_loss(self):
        def broken(*_):
            raise threading.BrokenBarrierError()

        with pytest.raises(PeerLostError):
            _elastic_wrap(broken)("x")

        def already(*_):
            raise PeerLostError("gone", missing=(3,))

        with pytest.raises(PeerLostError) as ei:
            _elastic_wrap(already)("x")
        assert ei.value.missing == (3,)  # not double-wrapped

    def test_membership_is_a_known_telemetry_kind(self):
        assert "membership" in KNOWN_KINDS


# ---------------------------------------------------------------------------
# Mesh geometry: dp absorbs the host change
# ---------------------------------------------------------------------------


class TestElasticMeshKwargs:
    BASE = dict(dp=8, pp=1, cp=1, ep=1, tp=2)

    def test_shrink_halves_dp_only(self):
        out = elastic_mesh_kwargs(self.BASE, hosts_before=4, hosts_after=2)
        assert out == dict(dp=4, pp=1, cp=1, ep=1, tp=2)

    def test_grow_restores_dp(self):
        shrunk = elastic_mesh_kwargs(
            self.BASE, hosts_before=4, hosts_after=2)
        regrown = elastic_mesh_kwargs(
            shrunk, hosts_before=2, hosts_after=4)
        assert regrown == self.BASE

    def test_unshrinkable_dp_refuses_loudly(self):
        with pytest.raises(MeshShrinkError, match="fleet restart"):
            elastic_mesh_kwargs(
                dict(self.BASE, dp=6), hosts_before=4, hosts_after=3)

    def test_bad_host_counts_refused(self):
        with pytest.raises(MeshShrinkError):
            elastic_mesh_kwargs(self.BASE, hosts_before=4, hosts_after=0)


# ---------------------------------------------------------------------------
# Checkpoint topology round-trip: dp4 -> dp2 -> dp4, bitwise
# ---------------------------------------------------------------------------


class TestCheckpointReshard:
    def _cm(self, tmp_path):
        from scaletorch_tpu.utils.checkpoint import CheckpointManager

        return CheckpointManager(str(tmp_path), async_save=False,
                                 retries=0, retry_base_delay=0.01)

    def test_dp4_dp2_dp4_round_trip_is_bitwise(self, tmp_path, devices8):
        mm4 = MeshManager(dp=4, tp=2)
        # the post-shrink world: half the hosts -> half the devices
        mm2 = MeshManager(dp=2, tp=2, devices=devices8[:4])
        rng = np.random.default_rng(0)
        host_params = {
            "w": rng.standard_normal((8, 8)).astype(np.float32),
            "b": rng.standard_normal((8,)).astype(np.float32),
        }
        host_opt = {"m": rng.standard_normal((8, 8)).astype(np.float32)}

        def place(mesh, tree, specs):
            return {k: jax.device_put(v, NamedSharding(mesh, specs[k]))
                    for k, v in tree.items()}

        p_specs = {"w": P("dp", "tp"), "b": P()}
        o_specs = {"m": P("dp", "tp")}
        params4 = place(mm4.mesh, host_params, p_specs)
        opt4 = place(mm4.mesh, host_opt, o_specs)
        cm = self._cm(tmp_path)
        assert cm.save(1, params=params4, opt_state=opt4,
                       extra={"samples_per_step": 16})
        cm.wait()

        out2 = cm.load_latest(params=params4, opt_state=opt4,
                              target_mesh=mm2.mesh)
        assert out2 is not None and out2["step"] == 1
        for k in host_params:
            leaf = out2["params"][k]
            # resharded onto the SMALLER mesh, same spec, bitwise values
            assert dict(leaf.sharding.mesh.shape)["dp"] == 2
            assert leaf.sharding.spec == p_specs[k]
            np.testing.assert_array_equal(np.asarray(leaf), host_params[k])
        np.testing.assert_array_equal(
            np.asarray(out2["opt_state"]["m"]), host_opt["m"])

        # scale back up: the dp2-resident arrays are the restore
        # templates this time (exactly the grow path)
        out4 = cm.load_latest(params=out2["params"],
                              opt_state=out2["opt_state"],
                              target_mesh=mm4.mesh)
        assert out4 is not None
        for k in host_params:
            leaf = out4["params"][k]
            assert dict(leaf.sharding.mesh.shape)["dp"] == 4
            np.testing.assert_array_equal(np.asarray(leaf), host_params[k])
        np.testing.assert_array_equal(
            np.asarray(out4["opt_state"]["m"]), host_opt["m"])
        assert out4["extra"]["samples_per_step"] == 16

    def test_retarget_tree_replicates_unsharded_leaves(self, devices8):
        from scaletorch_tpu.utils.checkpoint import retarget_tree

        mm2 = MeshManager(dp=2, tp=2, devices=devices8[:4])
        tree = {"host": np.ones((4,), np.float32), "scalar": 3}
        out = retarget_tree(tree, mm2.mesh)
        assert out["host"].shape == (4,)
        assert out["host"].sharding.spec == P()
        assert out["scalar"].shape == ()


# ---------------------------------------------------------------------------
# Loader position remap: every consumed batch retired exactly once
# ---------------------------------------------------------------------------


def _rows(n=64, seq=8):
    # each sequence row is its own index everywhere: batch contents
    # identify exactly which samples were consumed
    return np.tile(np.arange(n, dtype=np.int32)[:, None], (1, seq + 1))


def _loader(tokens, dp):
    return MicroBatchDataLoader(
        tokens, micro_batch_size=1, gradient_accumulation_steps=1,
        data_parallel_size=dp, seed=7)


def _drawn_samples(batch):
    return sorted(np.unique(batch["input_ids"]).tolist())


class TestLoaderRemap:
    def test_remap_arithmetic(self):
        assert remap_loader_position(
            3, old_samples_per_step=4, new_samples_per_step=2) == 6
        assert remap_loader_position(
            0, old_samples_per_step=4, new_samples_per_step=2) == 0
        assert remap_loader_position(
            5, old_samples_per_step=4, new_samples_per_step=4) == 5
        # non-exact grow rounds UP: partially-covered step batch retired
        assert remap_loader_position(
            3, old_samples_per_step=2, new_samples_per_step=4) == 2
        with pytest.raises(ValueError):
            remap_loader_position(
                1, old_samples_per_step=0, new_samples_per_step=4)
        with pytest.raises(ValueError):
            remap_loader_position(
                -1, old_samples_per_step=2, new_samples_per_step=4)

    def test_remap_never_replays_a_consumed_sample(self):
        for pos in range(0, 9):
            for old in (2, 3, 4, 8):
                for new in (2, 3, 4, 8):
                    got = remap_loader_position(
                        pos, old_samples_per_step=old,
                        new_samples_per_step=new)
                    consumed = pos * old
                    assert got * new >= consumed  # nothing double-counted
                    # and strictly less than one new step batch skipped
                    assert got * new - consumed < new

    def test_divisor_shrink_is_exact_end_to_end(self):
        tokens = _rows()
        big = _loader(tokens, dp=4)       # samples_per_step = 4
        it = iter(big)
        consumed = []
        for _ in range(3):
            consumed += _drawn_samples(next(it))
        new_pos = remap_loader_position(
            big.position, old_samples_per_step=big.samples_per_step,
            new_samples_per_step=2)
        assert new_pos == 6
        small = _loader(tokens, dp=2)      # samples_per_step = 2
        small.set_state(new_pos)
        # reference: an undisturbed dp2 walk of the SAME permutation
        ref = _loader(tokens, dp=2)
        ref_it = iter(ref)
        ref_consumed = []
        for _ in range(6):
            ref_consumed += _drawn_samples(next(ref_it))
        # the dp4 prefix covered exactly the first 6 dp2 steps' samples
        assert sorted(consumed) == sorted(ref_consumed)
        # and the remapped stream continues IDENTICALLY to the reference
        small_it = iter(small)
        for _ in range(4):
            a, b = next(small_it), next(ref_it)
            np.testing.assert_array_equal(a["input_ids"], b["input_ids"])

    def test_non_exact_grow_skips_lt_one_step_and_warns(self):
        import logging

        tokens = _rows()
        small = _loader(tokens, dp=2)      # spp 2
        it = iter(small)
        consumed = []
        for _ in range(3):                 # 6 samples consumed
            consumed += _drawn_samples(next(it))
        # the package logger does not propagate to root (so caplog
        # misses it): attach a capture handler directly
        records = []
        handler = logging.Handler()
        handler.emit = records.append
        pkg_logger = logging.getLogger("scaletorch_tpu")
        pkg_logger.addHandler(handler)
        try:
            new_pos = remap_loader_position(
                small.position, old_samples_per_step=2,
                new_samples_per_step=4)
        finally:
            pkg_logger.removeHandler(handler)
        assert new_pos == 2                # 8 samples retired, 2 skipped
        assert any("rounding up" in r.getMessage() for r in records)
        big = _loader(tokens, dp=4)
        big.set_state(new_pos)
        nxt = _drawn_samples(next(iter(big)))
        # never re-consumes anything already trained on
        assert not set(nxt) & set(consumed)

    def test_set_data_parallel_size_validates(self):
        tokens = _rows(n=8)
        loader = _loader(tokens, dp=2)
        with pytest.raises(ValueError):
            loader.set_data_parallel_size(0)
        with pytest.raises(ValueError, match="after the dp change"):
            loader.set_data_parallel_size(16)
        loader.set_data_parallel_size(4)
        assert loader.samples_per_step == 4
        syn = SyntheticDataLoader(
            vocab_size=16, sequence_length=8, micro_batch_size=2,
            gradient_accumulation_steps=1, data_parallel_size=2)
        syn.set_data_parallel_size(4)
        assert syn.global_batch_size == 8
        with pytest.raises(ValueError):
            syn.set_data_parallel_size(0)

    def test_load_checkpoint_remaps_position_across_dp_change(
            self, tmp_path):
        cfg = e2e_cfg(tmp_path, total_train_steps=4)
        t = ToyTrainer(cfg, e2e_tokens())
        t.train()  # saves step 4 with samples_per_step=4, position=4
        t.close()
        t2 = ToyTrainer(cfg, e2e_tokens())
        t2.loader.set_data_parallel_size(2)  # spp 4 -> 8
        assert t2.load_checkpoint()
        assert t2.global_step == 4
        # 16 samples consumed = exactly 2 steps of the new geometry
        assert t2.loader.position == 2
        assert t2._loader_skew == -2

    def test_remap_composes_with_rollback_skew(self, tmp_path):
        # PR-1 rollback skew: the retired anomalous batch keeps position
        # AHEAD of global_step; a dp change must remap that skewed
        # position, not the step counter
        cfg = e2e_cfg(tmp_path, divergence_policy="rollback",
                      ft_nan_at_step=3)
        t = ToyTrainer(cfg, e2e_tokens())
        t.train()   # ends step 6, position 7 (skew 1), saved at step 6
        t.close()
        assert t.loader.position == 7
        t2 = ToyTrainer(cfg, e2e_tokens())
        t2.resilience.injector.nan_at_step = 0
        t2.loader.set_data_parallel_size(2)  # spp 4 -> 8
        assert t2.load_checkpoint()
        assert t2.global_step == 6
        # 28 samples consumed -> ceil to 4 new steps (32 retired):
        # the skipped anomalous region stays retired
        assert t2.loader.position == 4
        assert t2._loader_skew == -2


# ---------------------------------------------------------------------------
# Fault injector drills + env parity
# ---------------------------------------------------------------------------


class TestElasticInjector:
    def test_kill_targets_one_host_and_fires_once(self):
        fired = []
        inj = FaultInjector(kill_host_at_step=3, kill_host=1,
                            host_index=0, deliver_kill=lambda: fired.append(1))
        inj.maybe_kill(3)
        assert fired == []          # not this host
        inj.host_index = 1
        inj.maybe_kill(2)
        assert fired == []          # not this step
        inj.maybe_kill(3)
        inj.maybe_kill(3)
        assert fired == [1]         # exactly once
        assert inj.active

    def test_default_kill_delivery_raises_nothing_catchable(self):
        # the test delivery is a BaseException by design
        with pytest.raises(HostKilledError):
            _raise_killed()
        assert not issubclass(HostKilledError, Exception)

    def test_elastic_hang_stalls_once(self):
        inj = FaultInjector(host_hang_elastic=2, host_hang_seconds=0.05,
                            host_index=0)
        t0 = time.monotonic()
        inj.maybe_elastic_hang(2)
        assert time.monotonic() - t0 >= 0.05
        t0 = time.monotonic()
        inj.maybe_elastic_hang(2)   # fired already
        assert time.monotonic() - t0 < 0.05
        assert inj.active

    def test_env_overrides_config(self, monkeypatch):
        cfg = e2e_cfg()
        monkeypatch.setenv("SCALETORCH_TPU_FT_KILL_HOST_STEP", "7")
        monkeypatch.setenv("SCALETORCH_TPU_FT_KILL_HOST", "2")
        monkeypatch.setenv("SCALETORCH_TPU_FT_HOST_HANG_ELASTIC", "4")
        inj = FaultInjector.from_config(cfg)
        assert inj.kill_host_at_step == 7
        assert inj.kill_host == 2
        assert inj.host_hang_elastic == 4

    def test_present_env_cancels_config_armed_drill(self, monkeypatch):
        cfg = e2e_cfg(ft_kill_host_at_step=9)
        monkeypatch.setenv("SCALETORCH_TPU_FT_KILL_HOST_STEP", "0")
        assert FaultInjector.from_config(cfg).kill_host_at_step == 0


# ---------------------------------------------------------------------------
# Parse-time rejection matrix
# ---------------------------------------------------------------------------


class TestElasticConfigValidation:
    def _cfg(self, tmp_path=None, **kw):
        base = dict(elastic=True, resume="auto")
        if tmp_path is not None:
            base["checkpoint_dir"] = str(tmp_path)
        base.update(kw)
        return ScaleTorchTPUArguments(**base)

    def test_valid_elastic_config_parses(self, tmp_path):
        cfg = self._cfg(tmp_path, num_processes=4, data_parallel_size=8,
                        elastic_min_hosts=2)
        assert cfg.elastic and cfg.elastic_min_hosts == 2

    def test_requires_checkpoint_dir(self):
        with pytest.raises(ValueError, match="checkpoint_dir"):
            self._cfg()

    def test_requires_resume(self, tmp_path):
        with pytest.raises(ValueError, match="--resume auto"):
            self._cfg(tmp_path, resume="off")

    def test_resume_must_composes(self, tmp_path):
        assert self._cfg(tmp_path, resume="must").resume == "must"

    def test_min_hosts_above_fleet_rejected(self, tmp_path):
        with pytest.raises(ValueError, match="elastic_min_hosts"):
            self._cfg(tmp_path, num_processes=2, data_parallel_size=2,
                      elastic_min_hosts=4)

    def test_host_spanning_model_axes_rejected(self, tmp_path):
        # dp not divisible by host count means tp/pp/cp/ep span hosts
        with pytest.raises(ValueError, match="divisible"):
            self._cfg(tmp_path, num_processes=4, data_parallel_size=6)

    def test_knob_ranges(self, tmp_path):
        for kw in (dict(ft_kill_host_at_step=-1),
                   dict(ft_host_hang_elastic=-2),
                   dict(ft_kill_host=-5),
                   dict(ft_host_hang_seconds=0.0),
                   dict(elastic_min_hosts=0),
                   dict(elastic_heartbeat_seconds=0.0),
                   dict(elastic_deadline_seconds=-1.0)):
            with pytest.raises(ValueError):
                self._cfg(tmp_path, **kw)
        # -1 is the documented "any host" sentinel for the drills
        assert self._cfg(tmp_path, ft_kill_host=-1).ft_kill_host == -1
