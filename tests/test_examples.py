"""Examples tier CI: both example mains run to DECREASING loss on the
virtual mesh (VERDICT r1 missing #6 — BASELINE configs 1-2 end-to-end).

The mains are imported and driven in-process (fast: shares the 8-device
CPU backend the conftest set up) with small step budgets.
"""

from __future__ import annotations

import os
import sys

import pytest

# Heavyweight end-to-end tier (VERDICT r3 weak #7): full runs, not CI units
pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def test_mnist_example_learns(capsys):
    from examples.mnist.train_mnist import main

    last_loss = main([
        "--epochs", "2", "--batch_size", "128", "--log_interval", "1000",
    ])
    # synthetic digits: NLL starts at ln(10) ~ 2.30 and must clearly drop
    assert last_loss < 1.6
    out = capsys.readouterr().out
    assert "test acc" in out


def test_mnist_example_fsdp_smoke():
    from examples.mnist.train_mnist import main

    last_loss = main([
        "--epochs", "1", "--batch_size", "128", "--fsdp",
        "--limit_steps", "6", "--log_interval", "1000",
    ])
    assert last_loss < 3.0  # ran and produced a finite loss


def test_mingpt_example_learns(capsys):
    from examples.mingpt.train_mingpt import main

    eval_nll = main([
        "--steps", "120", "--eval_interval", "60", "--batch_size", "32",
        "--block_size", "64", "--sample_tokens", "8",
    ])
    # char-LM over the repeated Zen corpus: from ~ln(vocab) toward memorised
    assert eval_nll < 2.4
    out = capsys.readouterr().out
    assert "sample:" in out


def test_mingpt_example_moe_smoke():
    from examples.mingpt.train_mingpt import main

    eval_nll = main([
        "--steps", "30", "--eval_interval", "30", "--batch_size", "16",
        "--block_size", "64", "--use_moe", "true", "--sample_tokens", "4",
        "--eval_batches", "2",
    ])
    assert eval_nll < 4.0


def test_fsdp_example_trains_and_resumes(tmp_path, capsys):
    """Reference examples/FSDP2 flow: first run saves, second resumes."""
    from examples.fsdp.train_fsdp import main

    ckpt = str(tmp_path / "ckpt")
    loss1 = main(["--steps", "3", "--checkpoint-dir", ckpt, "--seq", "32"])
    out1 = capsys.readouterr().out
    assert "per-device" in out1 and "saved step 3" in out1
    loss2 = main(["--steps", "2", "--checkpoint-dir", ckpt, "--seq", "32"])
    out2 = capsys.readouterr().out
    assert "resumed from step 3" in out2 and "saved step 5" in out2
    import numpy as np

    assert np.isfinite(loss1) and np.isfinite(loss2)


def test_device_mesh_demos_all_pass(capsys):
    from examples.device_mesh.mesh_demos import main

    main()
    out = capsys.readouterr().out
    assert "all device-mesh demos passed" in out
    assert out.count("True") >= 2  # tp + sp numeric checks


def test_imagenet_example_learns(capsys):
    """Reference examples/torch_examples/imagenet flow: ResNet18 DP
    training with top-1/top-5 validation reaches well-above-chance
    accuracy on the synthetic class-prototype set."""
    from examples.imagenet.dist_train import main

    best_acc1 = main([
        "--image-size", "32", "--num-classes", "10", "--epochs", "3",
        "--batch-size", "64", "--train-samples", "512",
        "--val-samples", "128", "--width", "16", "--lr", "0.05",
        "--bn-momentum", "0.5", "--print-freq", "100",
    ])
    assert best_acc1 > 50.0  # chance is 10%
    out = capsys.readouterr().out
    assert "acc@5" in out and "data parallel" in out


def test_trainer_points_examples_models_at_their_mains():
    from scaletorch_tpu.config import ScaleTorchTPUArguments
    from scaletorch_tpu.trainer.trainer import build_model_config

    cfg = ScaleTorchTPUArguments(model_type="lenet")
    with pytest.raises(ValueError, match="examples/mnist"):
        build_model_config(cfg)
    cfg = ScaleTorchTPUArguments(model_type="gpt_moe")
    with pytest.raises(ValueError, match="examples/mingpt"):
        build_model_config(cfg)


def test_pipeline_example_all_engines(capsys):
    """Pipeline demo: every schedule trains to the same decreasing loss
    on the same data (they reorder compute, not math), and the
    interleaved run prints its tick accounting."""
    from examples.pipeline.train_pp import main

    last = {}
    interleaved_out = ""
    for engine in ("afab", "interleaved", "memory_chunked"):
        last[engine] = main([
            "--engine", engine, "--steps", "6", "--seq", "64",
        ])
        out = capsys.readouterr().out
        if engine == "interleaved":
            interleaved_out = out
        first = float(out.split("loss ")[1].split(" ->")[0])
        assert last[engine] < first  # it actually learns
    assert last["interleaved"] == pytest.approx(last["afab"], rel=1e-4)
    assert last["memory_chunked"] == pytest.approx(last["afab"], rel=1e-4)
    # the tick accounting printed up front
    assert "predicted step time" in interleaved_out
    assert "bubble" in interleaved_out


def test_moe_example_dispatch_and_interleaved(capsys):
    """MoE demo: learns under the index dispatch AND the interleaved
    dense/sparse architecture, and the two dispatch modes agree exactly
    at the same seed/geometry (identical routing math)."""
    from examples.moe.train_moe import main

    last = {}
    for dispatch in ("einsum", "index"):
        last[dispatch] = main([
            "--ep", "2", "--seq", "128", "--steps", "10",
            "--dispatch", dispatch,
        ])
        out = capsys.readouterr().out
        assert f"dispatch={dispatch}" in out
        first = float(out.split("loss ")[1].split(" ->")[0])
        assert last[dispatch] < first  # it actually learns
    assert last["index"] == pytest.approx(last["einsum"], rel=2e-4)

    # interleaved: layers 1,3 sparse / 0,2 dense
    main(["--ep", "2", "--seq", "128", "--steps", "4",
          "--sparse-step", "2"])
    assert "sparse_layers=[1, 3]" in capsys.readouterr().out


def test_longctx_example_both_strategies(capsys):
    """CP demo: the loss decreases under both distributed-attention
    strategies and the two agree at the same seed/geometry (both compute
    exact full attention)."""
    from examples.longctx.train_longctx import main

    last = {}
    for strategy in ("ring", "ulysses"):
        last[strategy] = main([
            "--cp", "2", "--seq", "256", "--steps", "6",
            "--strategy", strategy,
        ])
        out = capsys.readouterr().out
        assert f"strategy={strategy}" in out
        first = float(out.split("loss ")[1].split(" ->")[0])
        assert last[strategy] < first  # it actually learns
    assert last["ring"] == pytest.approx(last["ulysses"], rel=2e-4)
