"""Property tests for the log-bucketed histogram primitive.

The ISSUE-12 contract: bucket monotonicity, merge associativity, and
quantile-estimate bounds against a sorted-sample oracle — plus the
serialization round-trip, the registry's label-cardinality cap, and the
Prometheus histogram exposition (telemetry/export.render_families).
Pure stdlib under test; numpy only appears as a convenience RNG.
"""

import json
import math
import random

import pytest

from scaletorch_tpu.telemetry.export import (
    escape_label_value,
    format_labels,
    render_families,
    render_prometheus,
)
from scaletorch_tpu.telemetry.histogram import (
    DEFAULT_SCHEMA,
    OVERFLOW_LABEL,
    BucketSchema,
    LogHistogram,
    TenantHistograms,
)


def lognormal_samples(rng, n, mu=-3.0, sigma=2.0):
    """Latency-shaped positive samples spanning several decades."""
    return [math.exp(rng.gauss(mu, sigma)) for _ in range(n)]


class TestBucketSchema:
    def test_bounds_strictly_monotone(self):
        schema = DEFAULT_SCHEMA
        assert all(a < b for a, b in zip(schema.bounds, schema.bounds[1:]))

    def test_index_brackets_value(self):
        """Every value lands in the bucket whose (lower, upper] range
        contains it — including exact boundary values."""
        schema = BucketSchema(lo=1e-3, growth=2.0, count=10)
        rng = random.Random(0)
        values = ([0.0, 1e-9, 1e-3, 2e-3, schema.bounds[-1],
                   schema.bounds[-1] * 10]
                  + [b for b in schema.bounds]
                  + lognormal_samples(rng, 200))
        for v in values:
            i = schema.index(v)
            if i == schema.count:
                assert v > schema.bounds[-1]
            else:
                assert v <= schema.bounds[i]
                if i > 0:
                    assert v > schema.bounds[i - 1]

    def test_validation(self):
        with pytest.raises(ValueError):
            BucketSchema(lo=0.0)
        with pytest.raises(ValueError):
            BucketSchema(growth=1.0)
        with pytest.raises(ValueError):
            BucketSchema(count=0)


class TestLogHistogram:
    def test_counts_conserved_and_cumulative_monotone(self):
        rng = random.Random(1)
        h = LogHistogram()
        values = lognormal_samples(rng, 500) + [0.0, 1e9]
        for v in values:
            h.observe(v)
        assert h.count == len(values)
        assert sum(h.counts) == len(values)
        cum = h.cumulative()
        assert cum[-1] == (None, len(values))
        cs = [c for _, c in cum]
        assert all(a <= b for a, b in zip(cs, cs[1:]))
        les = [le for le, _ in cum[:-1]]
        assert all(a < b for a, b in zip(les, les[1:]))

    def test_negative_observations_clamp_to_zero(self):
        h = LogHistogram()
        h.observe(-1.0)
        assert h.count == 1 and h.min == 0.0 and h.sum == 0.0

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_quantile_bounds_vs_sorted_oracle(self, seed):
        """The estimate shares a bucket with the true order statistic:
        relative error is bounded by the schema growth factor (and the
        estimate always sits inside the observed [min, max])."""
        rng = random.Random(seed)
        # keep every sample above the lowest bound so the relative
        # bound is exact (bucket 0 only guarantees absolute error <= lo)
        values = [max(v, DEFAULT_SCHEMA.bounds[0] * 1.01)
                  for v in lognormal_samples(rng, 400)]
        h = LogHistogram()
        for v in values:
            h.observe(v)
        ordered = sorted(values)
        growth = DEFAULT_SCHEMA.growth
        for q in (0.0, 0.01, 0.1, 0.25, 0.5, 0.75, 0.9, 0.95, 0.99, 1.0):
            est = h.quantile(q)
            true = ordered[min(len(ordered) - 1,
                               max(0, math.ceil(q * len(ordered)) - 1))]
            assert h.min <= est <= h.max
            assert est <= true * growth * (1 + 1e-9), (q, est, true)
            assert est >= true / growth * (1 - 1e-9), (q, est, true)

    def test_quantile_empty_and_bad_q(self):
        h = LogHistogram()
        assert h.quantile(0.5) is None
        h.observe(1.0)
        with pytest.raises(ValueError):
            h.quantile(1.5)

    @pytest.mark.parametrize("seed", [0, 3])
    def test_merge_associative_and_equals_concatenation(self, seed):
        rng = random.Random(seed)
        parts = [lognormal_samples(rng, rng.randint(1, 80))
                 for _ in range(3)]

        def hist_of(values):
            h = LogHistogram()
            for v in values:
                h.observe(v)
            return h

        a, b, c = (hist_of(p) for p in parts)
        left = LogHistogram.combined(LogHistogram.combined(a, b), c)
        right = LogHistogram.combined(a, LogHistogram.combined(b, c))
        flat = hist_of([v for p in parts for v in p])
        for other in (right, flat):
            assert left.counts == other.counts
            assert left.count == other.count
            assert left.sum == pytest.approx(other.sum)
            assert left.min == other.min and left.max == other.max
        # the merged quantiles answer for the union
        assert left.quantile(0.5) == flat.quantile(0.5)

    def test_merge_schema_mismatch_raises(self):
        with pytest.raises(ValueError, match="schema"):
            LogHistogram(BucketSchema(lo=1e-4)).merge(
                LogHistogram(BucketSchema(lo=1e-3)))

    def test_dict_round_trip_is_sparse_and_exact(self):
        rng = random.Random(7)
        h = LogHistogram()
        for v in lognormal_samples(rng, 100):
            h.observe(v)
        obj = json.loads(json.dumps(h.to_dict()))  # through real JSON
        assert len(obj["buckets"]) < len(h.counts)  # sparse
        back = LogHistogram.from_dict(obj)
        assert back.counts == h.counts
        assert back.count == h.count
        assert back.quantile(0.9) == h.quantile(0.9)

    def test_from_dict_rejects_corrupt_records(self):
        h = LogHistogram()
        h.observe(1.0)
        bad = h.to_dict()
        bad["count"] = 5  # buckets no longer sum to count
        with pytest.raises(ValueError):
            LogHistogram.from_dict(bad)
        worse = h.to_dict()
        worse["buckets"] = {"9999": 1}
        with pytest.raises(ValueError):
            LogHistogram.from_dict(worse)


class TestTenantHistograms:
    def test_observe_get_merged(self):
        reg = TenantHistograms(("ttft", "e2e"))
        reg.observe("ttft", "a", 0.1)
        reg.observe("ttft", "a", 0.2)
        reg.observe("ttft", "b", 0.4)
        assert reg.get("ttft", "a").count == 2
        assert reg.get("ttft", "missing") is None
        merged = reg.merged("ttft")
        assert merged.count == 3
        assert reg.merged("e2e") is None
        assert reg.total_count() == 3

    def test_label_cardinality_cap_aggregates_not_drops(self):
        reg = TenantHistograms(("ttft",), max_labels=4)
        for i in range(10):
            reg.observe("ttft", f"tenant{i}", 0.1)
        series = reg.series("ttft")
        assert len(series) <= 5  # 4 real labels + _other
        assert OVERFLOW_LABEL in series
        # every observation kept: attribution coarsened, data intact
        assert reg.merged("ttft").count == 10

    def test_record_round_trip_and_merge(self):
        reg = TenantHistograms(("ttft",))
        reg.observe("ttft", "a", 0.1)
        reg.observe("ttft", "b", 0.2)
        record = json.loads(json.dumps(reg.to_record()))
        other = TenantHistograms(("ttft",))
        other.merge_record(record)
        other.merge_record(record)  # merging twice doubles counts
        assert other.merged("ttft").count == 4


class TestPrometheusRendering:
    def test_label_escaping_of_hostile_values(self):
        hostile = 'evil"} 1\nfake_metric{x="'
        escaped = escape_label_value(hostile)
        assert "\n" not in escaped
        assert '\\"' in escaped
        text = format_labels({"tenant": hostile})
        assert text.count("\n") == 0

    def test_families_gauge_counter_histogram(self):
        h = LogHistogram(BucketSchema(lo=1.0, growth=2.0, count=4))
        h.observe(1.5)
        h.observe(100.0)  # overflow bucket
        text = render_families([
            {"name": "depth", "type": "gauge",
             "samples": [({"tenant": "a"}, 3), ({"tenant": "b"}, 1)]},
            {"name": "sheds", "type": "counter", "samples": [(None, 7)]},
            {"name": "ttft_seconds", "type": "histogram",
             "series": [({"tenant": "a"}, h)]},
        ])
        assert "# TYPE scaletorch_depth gauge" in text
        assert 'scaletorch_depth{tenant="a"} 3.0' in text
        assert "# TYPE scaletorch_sheds counter" in text
        assert "scaletorch_sheds 7.0" in text
        assert "# TYPE scaletorch_ttft_seconds histogram" in text
        assert ('scaletorch_ttft_seconds_bucket{le="2",tenant="a"} 1'
                in text)
        assert ('scaletorch_ttft_seconds_bucket{le="+Inf",tenant="a"} 2'
                in text)
        assert 'scaletorch_ttft_seconds_count{tenant="a"} 2' in text
        assert 'scaletorch_ttft_seconds_sum{tenant="a"} 101.5' in text

    def test_family_series_share_one_le_set(self):
        """Series of one family are padded to a common le set: a
        consumer summing cumulative counts across label sets per le
        (Prometheus aggregation, slo_check's scrape parser) must see a
        monotone sequence — tail elision per-series would make a fast
        tenant's observations vanish above its own max bucket."""
        schema = BucketSchema(lo=1e-3, growth=2.0, count=20)
        fast, slow = LogHistogram(schema), LogHistogram(schema)
        for _ in range(100):
            fast.observe(0.002)   # low bucket only
        for _ in range(100):
            slow.observe(10.0)    # high bucket
        text = render_families([
            {"name": "ttft_seconds", "type": "histogram",
             "series": [({"tenant": "fast"}, fast),
                        ({"tenant": "slow"}, slow)]},
        ])
        import re

        summed = {}
        for m in re.finditer(
                r'ttft_seconds_bucket\{le="([^"]+)",tenant="\w+"\} (\d+)',
                text):
            summed[m.group(1)] = summed.get(m.group(1), 0) + int(m.group(2))
        les = sorted(
            (float("inf") if le == "+Inf" else float(le), c)
            for le, c in summed.items())
        counts = [c for _, c in les]
        assert all(a <= b for a, b in zip(counts, counts[1:])), les
        # both series expose every le, so the fast tenant's 100
        # observations never drop out of the summed cumulative counts
        # once past their bucket — without padding, every le above the
        # fast tenant's top emitted bucket would dip back to slow-only
        assert all(c >= 100 for le, c in les if le >= 0.002), les
        assert counts[-1] == 200

    def test_cumulative_min_buckets_padding(self):
        h = LogHistogram(BucketSchema(lo=1.0, growth=2.0, count=8))
        h.observe(1.0)  # bucket 0 only
        assert len(h.cumulative()) == 2  # bucket 0 + +Inf
        padded = h.cumulative(min_buckets=5)
        assert len(padded) == 6
        assert all(c == 1 for _, c in padded)
        # min_buckets clamps at the schema size
        assert len(h.cumulative(min_buckets=99)) == 9

    def test_bad_family_type_raises(self):
        with pytest.raises(ValueError, match="type"):
            render_families([{"name": "x", "type": "summary",
                              "samples": [(None, 1)]}])

    def test_render_prometheus_back_compat(self):
        body = render_prometheus(
            {"tokens/s": 5.0, "occupancy": 0.5, "label": "skip-me"})
        assert "# TYPE scaletorch_occupancy gauge" in body
        assert "scaletorch_occupancy 0.5" in body
        assert "scaletorch_tokens_s 5.0" in body
        assert "skip-me" not in body
