"""Fault tolerance (scaletorch_tpu/resilience.py + integrations).

Three layers of coverage, all in the quick tier:

  * unit — DivergenceSentinel policies, retry_with_backoff,
    PreemptionHandler, FaultInjector, ResilienceManager protocol, and the
    in-jit non-finite update guard (trainer/train_step.guarded_update).
  * CheckpointManager hardening — injected save failures retried with
    backoff, exhausted retries never raising, async->sync degradation,
    corrupted-latest fallback to the previous step.
  * end-to-end inject -> recover — a ``ToyTrainer`` that keeps the REAL
    ``Trainer.train`` loop, rollback, emergency-checkpoint and save/load
    code and swaps only the mesh/SPMD step for a tiny jit model (the 5D
    SPMD step needs newer JAX than the quick-tier container provides;
    the full-Trainer variants live in
    tests/trainer/test_resilient_trainer.py under the slow marker).
"""

import os
import signal

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scaletorch_tpu.config import ScaleTorchTPUArguments
from scaletorch_tpu.resilience import (
    DivergenceSentinel,
    FaultInjector,
    PreemptionHandler,
    ResilienceManager,
    TrainingDivergedError,
    retry_with_backoff,
)

# ---------------------------------------------------------------------------
# DivergenceSentinel
# ---------------------------------------------------------------------------


class TestDivergenceSentinel:
    def test_healthy_losses_feed_ema(self):
        s = DivergenceSentinel(ema_beta=0.5)
        assert s.observe(4.0) == "ok"
        assert s.observe(2.0) == "ok"
        assert s.ema == pytest.approx(3.0)
        assert s.total_anomalies == 0

    def test_nonfinite_is_anomalous_and_skips(self):
        s = DivergenceSentinel(policy="skip")
        s.observe(4.0)
        assert s.observe(float("nan")) == "skip"
        assert s.observe(float("inf")) == "skip"
        assert s.nonfinite_losses == 2
        # anomalies never feed the EMA
        assert s.ema == pytest.approx(4.0)

    def test_spike_detection_needs_warm_ema(self):
        s = DivergenceSentinel(policy="skip", spike_factor=2.0)
        assert s.observe(100.0) == "ok"  # first loss warms the EMA
        assert s.observe(50.0) == "ok"
        assert s.observe(1000.0) == "skip"
        assert s.loss_spikes == 1

    def test_abort_policy_raises_immediately(self):
        s = DivergenceSentinel(policy="abort")
        s.observe(1.0)
        with pytest.raises(TrainingDivergedError, match="abort"):
            s.observe(float("nan"))

    def test_consecutive_anomalies_abort_any_policy(self):
        s = DivergenceSentinel(policy="skip", max_consecutive_anomalies=3)
        s.observe(1.0)
        assert s.observe(float("nan")) == "skip"
        assert s.observe(float("nan")) == "skip"
        with pytest.raises(TrainingDivergedError, match="consecutive"):
            s.observe(float("nan"))

    def test_healthy_step_resets_consecutive(self):
        s = DivergenceSentinel(policy="skip", max_consecutive_anomalies=2)
        s.observe(1.0)
        s.observe(float("nan"))
        s.observe(1.0)
        assert s.consecutive == 0
        s.observe(float("nan"))  # starts a fresh streak, below the cap
        assert s.total_anomalies == 2

    def test_rollback_budget_aborts_before_the_excess_restore(self):
        s = DivergenceSentinel(policy="rollback", max_rollbacks=2)
        s.ensure_rollback_budget()
        s.note_rollback()
        s.ensure_rollback_budget()
        s.note_rollback()
        # the abort fires BEFORE rollback #3 performs its restore
        with pytest.raises(TrainingDivergedError, match="rollback"):
            s.ensure_rollback_budget()

    def test_bad_policy_rejected(self):
        with pytest.raises(ValueError, match="policy"):
            DivergenceSentinel(policy="explode")

    def test_counters_shape(self):
        s = DivergenceSentinel()
        s.observe(1.0)
        s.observe(float("nan"))
        assert s.counters() == {
            "anomalies": 1.0, "nonfinite_losses": 1.0,
            "loss_spikes": 0.0, "rollbacks": 0.0,
        }


# ---------------------------------------------------------------------------
# retry_with_backoff
# ---------------------------------------------------------------------------


class TestRetryWithBackoff:
    def test_succeeds_after_transient_failures(self):
        calls, sleeps = [], []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("transient")
            return "done"

        out = retry_with_backoff(
            flaky, retries=3, base_delay=0.25, jitter=0.0,
            sleep=sleeps.append,
        )
        assert out == "done"
        assert len(calls) == 3
        # exponential: 0.25 then 0.5
        assert sleeps == pytest.approx([0.25, 0.5])

    def test_exhausted_retries_reraise(self):
        sleeps = []
        with pytest.raises(OSError, match="persistent"):
            retry_with_backoff(
                lambda: (_ for _ in ()).throw(OSError("persistent")),
                retries=2, base_delay=0.01, sleep=sleeps.append,
            )
        assert len(sleeps) == 2

    def test_delay_capped_and_jittered(self):
        sleeps = []
        calls = []

        def fail_then_ok():
            calls.append(1)
            if len(calls) < 5:
                raise OSError("x")
            return 1

        retry_with_backoff(
            fail_then_ok, retries=4, base_delay=1.0, max_delay=2.0,
            jitter=0.5, sleep=sleeps.append,
        )
        assert all(d <= 2.0 * 1.5 for d in sleeps)
        assert sleeps[2] >= 2.0  # capped base, pre-jitter >= max_delay

    def test_non_retriable_passes_through(self):
        with pytest.raises(KeyboardInterrupt):
            retry_with_backoff(
                lambda: (_ for _ in ()).throw(KeyboardInterrupt()),
                retries=5, base_delay=0.01, sleep=lambda _: None,
            )


# ---------------------------------------------------------------------------
# PreemptionHandler
# ---------------------------------------------------------------------------


class TestPreemptionHandler:
    def test_real_sigterm_sets_flag_and_uninstall_restores(self):
        prev = signal.getsignal(signal.SIGTERM)
        h = PreemptionHandler()
        with h:
            assert not h.requested
            os.kill(os.getpid(), signal.SIGTERM)
            assert h.requested
            assert h.signum == signal.SIGTERM
        assert signal.getsignal(signal.SIGTERM) is prev

    def test_second_sigint_falls_through_to_keyboardinterrupt(self):
        h = PreemptionHandler()
        h.trigger(signal.SIGINT)
        assert h.requested
        with pytest.raises(KeyboardInterrupt):
            h.trigger(signal.SIGINT)

    def test_sigterm_then_one_sigint_stays_graceful(self):
        # only REPEATED SIGINTs escalate; SIGTERM + one ctrl-C must still
        # get the graceful emergency-checkpoint path
        h = PreemptionHandler()
        h.trigger(signal.SIGTERM)
        h.trigger(signal.SIGINT)  # must NOT raise
        assert h.requested

    def test_trigger_simulates_without_real_signal(self):
        h = PreemptionHandler()
        h.trigger()
        assert h.requested


# ---------------------------------------------------------------------------
# FaultInjector
# ---------------------------------------------------------------------------


class TestFaultInjector:
    def test_nan_fires_once_at_step(self):
        inj = FaultInjector(nan_at_step=3)
        m = inj.corrupt_metrics(2, {"loss": 1.0})
        assert m["loss"] == 1.0
        m = inj.corrupt_metrics(3, {"loss": 1.0})
        assert np.isnan(m["loss"])
        # rollback re-reaches step 3: must not fire twice
        m = inj.corrupt_metrics(3, {"loss": 1.0})
        assert m["loss"] == 1.0

    def test_save_failures_consumed(self):
        inj = FaultInjector(fail_saves=2)
        assert inj.take_save_failure()
        assert inj.take_save_failure()
        assert not inj.take_save_failure()

    def test_from_config_env_overrides(self, monkeypatch):
        cfg = ScaleTorchTPUArguments(ft_nan_at_step=5)
        inj = FaultInjector.from_config(cfg)
        assert inj.nan_at_step == 5
        monkeypatch.setenv("SCALETORCH_TPU_FT_NAN_STEP", "9")
        assert FaultInjector.from_config(cfg).nan_at_step == 9

    def test_env_zero_cancels_config_armed_drill(self, monkeypatch):
        # a PRESENT env var wins even at 0, so a restarted job can cancel
        # a drill baked into its config without a config edit
        cfg = ScaleTorchTPUArguments(ft_sigterm_at_step=100)
        monkeypatch.setenv("SCALETORCH_TPU_FT_SIGTERM_STEP", "0")
        assert FaultInjector.from_config(cfg).sigterm_at_step == 0

    def test_inactive_by_default(self):
        assert not FaultInjector().active


# ---------------------------------------------------------------------------
# ResilienceManager protocol
# ---------------------------------------------------------------------------


class TestResilienceManager:
    def test_ok_path_untouched(self):
        rm = ResilienceManager(sentinel=DivergenceSentinel())
        m, action = rm.after_step(1, {"loss": 2.0})
        assert action == "ok" and m["loss"] == 2.0

    def test_skip_on_injected_nan(self):
        rm = ResilienceManager(
            sentinel=DivergenceSentinel(policy="skip"),
            injector=FaultInjector(nan_at_step=2),
        )
        rm.after_step(1, {"loss": 2.0})
        m, action = rm.after_step(2, {"loss": 2.0})
        assert action == "skip" and np.isnan(m["loss"])

    def test_rollback_callback_invoked_and_counted(self):
        rm = ResilienceManager(sentinel=DivergenceSentinel(policy="rollback"))
        rm.after_step(1, {"loss": 2.0})
        rolled = []
        _, action = rm.after_step(
            2, {"loss": float("nan")},
            rollback=lambda: rolled.append(1) or True,
        )
        assert action == "rollback" and rolled
        assert rm.sentinel.rollbacks == 1

    def test_rollback_without_checkpoint_downgrades_to_skip(self):
        rm = ResilienceManager(sentinel=DivergenceSentinel(policy="rollback"))
        rm.after_step(1, {"loss": 2.0})
        _, action = rm.after_step(2, {"loss": float("nan")},
                                  rollback=lambda: False)
        assert action == "skip"
        assert rm.sentinel.rollbacks == 0

    def test_from_config_disabled_sentinel(self):
        cfg = ScaleTorchTPUArguments(sentinel_frequency=0)
        rm = ResilienceManager.from_config(cfg)
        assert rm.sentinel is None
        m, action = rm.after_step(1, {"loss": float("nan")})
        assert action == "ok"  # host sentinel off; in-jit guard still runs

    def test_injected_nan_observed_even_off_sample_cadence(self):
        # a drill must not be silently ignored because its step doesn't
        # land on the sentinel's sampling cadence
        rm = ResilienceManager(
            sentinel=DivergenceSentinel(policy="skip"),
            injector=FaultInjector(nan_at_step=3),
            sentinel_frequency=10,
        )
        _, a = rm.after_step(1, {"loss": 1.0})
        assert a == "ok"  # off-cadence, not sampled
        m, a = rm.after_step(3, {"loss": 1.0})
        assert a == "skip" and np.isnan(m["loss"])

    def test_from_config_default_follows_log_frequency(self):
        # -1 (default) resolves to the logging cadence, where the loss
        # host-sync is already paid — no extra sync on the hot path
        cfg = ScaleTorchTPUArguments(log_frequency=10)
        rm = ResilienceManager.from_config(cfg)
        assert rm.sentinel_frequency == 10
        assert ResilienceManager.from_config(
            ScaleTorchTPUArguments(log_frequency=10, sentinel_frequency=1)
        ).sentinel_frequency == 1


# ---------------------------------------------------------------------------
# In-jit non-finite update guard (shared by spmd.py via guarded_update)
# ---------------------------------------------------------------------------

V, H, SEQ = 32, 8, 16


def toy_forward(params, ids, cfg, positions=None, attention_backend=None,
                gradient_checkpointing=False, **kw):
    """make_train_step's model contract on a 2-matrix toy LM."""
    return params["embed"][ids] @ params["head"]


def toy_params(scale=0.1, seed=0):
    k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
    return {
        "embed": jax.random.normal(k1, (V, H), jnp.float32) * scale,
        "head": jax.random.normal(k2, (H, V), jnp.float32) * scale,
    }


def toy_batch(rng, accum=2, micro=2):
    toks = rng.integers(0, V, size=(accum, micro, SEQ + 1)).astype(np.int32)
    return {
        "input_ids": toks[:, :, :-1],
        "target_ids": toks[:, :, 1:],
        "position_ids": np.broadcast_to(
            np.arange(SEQ, dtype=np.int32), (accum, SEQ)).copy(),
    }


class TestNonfiniteGuard:
    def _step(self, **kw):
        from scaletorch_tpu.trainer.optimizer import create_optimizer
        from scaletorch_tpu.trainer.train_step import make_train_step

        args = ScaleTorchTPUArguments(learning_rate=1e-2)
        tx, _ = create_optimizer(args)
        return tx, make_train_step(toy_forward, object(), tx, donate=False,
                                   **kw)

    def test_finite_step_updates_and_reports_zero(self):
        tx, step = self._step()
        p = toy_params()
        o = tx.init(p)
        rng = np.random.default_rng(0)
        p2, o2, m = step(p, o, toy_batch(rng))
        assert float(m["update_skipped"]) == 0.0
        assert np.isfinite(float(m["loss"]))
        assert not np.allclose(p["embed"], p2["embed"])

    def test_nonfinite_loss_freezes_params_and_opt_state(self):
        tx, step = self._step()
        # poison ONE param so loss/grads are NaN inside the jitted step
        p = toy_params()
        p = {**p, "head": p["head"].at[0, 0].set(jnp.nan)}
        o = tx.init(toy_params())  # finite optimizer state
        rng = np.random.default_rng(0)
        p2, o2, m = step(p, o, toy_batch(rng))
        assert float(m["update_skipped"]) == 1.0
        # params bit-identical (update rejected); float opt state
        # (moments) frozen; INTEGER state (schedule counts) advances so
        # lr schedules stay aligned with the trainer's global_step
        for a, b in zip(jax.tree.leaves(p), jax.tree.leaves(p2)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        saw_count = False
        for a, b in zip(jax.tree.leaves(o), jax.tree.leaves(o2)):
            a, b = np.asarray(a), np.asarray(b)
            if np.issubdtype(b.dtype, np.integer):
                np.testing.assert_array_equal(a + 1, b)
                saw_count = True
            else:
                np.testing.assert_array_equal(a, b)
        assert saw_count  # adamw carries a schedule count

    def test_guard_off_keeps_legacy_metrics(self):
        tx, step = self._step(nonfinite_guard=False)
        p = toy_params()
        rng = np.random.default_rng(0)
        _, _, m = step(p, tx.init(p), toy_batch(rng))
        assert set(m) == {"loss", "grad_norm"}


# ---------------------------------------------------------------------------
# CheckpointManager hardening
# ---------------------------------------------------------------------------


def small_tree():
    return {"w": np.arange(6, dtype=np.float32).reshape(2, 3)}


class TestCheckpointRetries:
    def _cm(self, tmp_path, **kw):
        from scaletorch_tpu.utils.checkpoint import CheckpointManager

        kw.setdefault("retry_base_delay", 0.01)
        return CheckpointManager(str(tmp_path), async_save=False, **kw)

    def test_injected_failures_are_retried(self, tmp_path):
        inj = FaultInjector(fail_saves=2)
        cm = self._cm(tmp_path, retries=3, fault_injector=inj)
        assert cm.save(1, params=small_tree(), opt_state=small_tree())
        cm.wait()
        assert cm.all_steps() == [1]

    def test_exhausted_retries_return_false_not_raise(self, tmp_path):
        inj = FaultInjector(fail_saves=100)
        cm = self._cm(tmp_path, retries=2, fault_injector=inj)
        assert cm.save(1, params=small_tree(), opt_state=small_tree()) is False
        assert cm.all_steps() == []

    def test_async_failure_degrades_to_sync(self, tmp_path):
        from scaletorch_tpu.utils.checkpoint import CheckpointManager

        cm = CheckpointManager(str(tmp_path), async_save=True,
                               retries=1, retry_base_delay=0.01)
        broken = cm._mgr

        def boom(*a, **kw):
            raise RuntimeError("async pool died")

        broken.save = boom
        assert cm.save(1, params=small_tree(), opt_state=small_tree())
        assert cm._async is False and cm._mgr is not broken
        cm.wait()
        assert cm.all_steps() == [1]

    def test_wait_failure_degrades_to_sync(self, tmp_path):
        from scaletorch_tpu.utils.checkpoint import CheckpointManager

        cm = CheckpointManager(str(tmp_path), async_save=True,
                               retries=1, retry_base_delay=0.01)
        cm._mgr.wait_until_finished = lambda: (_ for _ in ()).throw(
            RuntimeError("pool dead"))
        cm.wait()  # must not raise
        assert cm._async is False

    def test_corrupted_latest_falls_back_to_previous(self, tmp_path):
        cm = self._cm(tmp_path, retries=0)
        t = small_tree()
        for step in (1, 2):
            assert cm.save(step, params={"w": t["w"] * step}, opt_state=t,
                           extra={"tokens_seen": step * 10})
        cm.wait()
        # corrupt step 2: drop the params payload subtree
        import shutil

        victim = next(p for p in (tmp_path / "2").iterdir()
                      if "param" in p.name)
        shutil.rmtree(victim)
        out = cm.load_latest(params=t, opt_state=t)
        assert out is not None and out["step"] == 1
        np.testing.assert_array_equal(out["params"]["w"], t["w"])
        assert out["extra"]["tokens_seen"] == 10
        # the unreadable step must be retired, or orbax's monotonic
        # should_save would silently reject every save in the retrain
        # window (steps <= the stale latest)
        assert cm.all_steps() == [1]
        assert cm.save(2, params=t, opt_state=t)
        cm.wait()
        assert cm.all_steps() == [1, 2]

    def test_all_checkpoints_unreadable_returns_none(self, tmp_path):
        cm = self._cm(tmp_path, retries=0)
        assert cm.load_latest(params=small_tree(),
                              opt_state=small_tree()) is None

    def test_multiprocess_disables_host_local_retry(self, tmp_path):
        # orbax save is a cross-process collective: a host-local retry
        # would re-enter it without peers, so multi-host runs keep the
        # one-attempt, exception-propagating semantics (the flag is set
        # from jax.process_count() at construction; forced here because
        # the test process is single-host)
        inj = FaultInjector(fail_saves=1)
        cm = self._cm(tmp_path, retries=3, fault_injector=inj)
        cm._single_process = False
        with pytest.raises(OSError, match="injected"):
            cm.save(1, params=small_tree(), opt_state=small_tree())


# ---------------------------------------------------------------------------
# End-to-end: inject -> recover through the REAL Trainer.train loop
# ---------------------------------------------------------------------------


class ToyTrainer:
    """The production resilience surface on a mesh-free step.

    Reuses Trainer.train / _rollback_to_last_good / _emergency_checkpoint /
    save_checkpoint / load_checkpoint / checkpoint_manager / _layer_storage
    UNMODIFIED (bound below) — only __init__ and step() differ, replacing
    the 5D SPMD step (which needs newer JAX than the quick tier has) with
    the toy jit model above. The fault paths under test are the real ones.
    """

    def __init__(self, cfg: ScaleTorchTPUArguments, tokens: np.ndarray):
        from scaletorch_tpu.data.dataloader import MicroBatchDataLoader
        from scaletorch_tpu.resilience import ResilienceManager
        from scaletorch_tpu.resilience_distributed import CoordinatedResilience
        from scaletorch_tpu.trainer.metrics import MetricsLogger
        from scaletorch_tpu.trainer.optimizer import create_optimizer
        from scaletorch_tpu.trainer.train_step import make_train_step
        from scaletorch_tpu.utils.logger import get_logger

        self.cfg = cfg
        self.logger = get_logger()
        self.tx, self.schedule = create_optimizer(cfg)
        self.step_fn = make_train_step(
            toy_forward, object(), self.tx, donate=False,
            nonfinite_guard=cfg.nonfinite_guard,
        )
        self.params = toy_params(seed=cfg.seed)
        self.opt_state = self.tx.init(self.params)
        self.resilience = ResilienceManager.from_config(cfg)
        self.coordinator = CoordinatedResilience.from_config(
            cfg, self.resilience)
        self._watchdog = None
        self.loader = MicroBatchDataLoader(
            tokens,
            micro_batch_size=cfg.micro_batch_size,
            gradient_accumulation_steps=cfg.gradient_accumulation_steps,
            seed=cfg.seed,
            read_retries=cfg.data_read_retries,
            retry_base_delay=cfg.data_retry_base_delay,
            max_skipped_batches=cfg.data_max_skipped_batches,
            fault_injector=self.resilience.injector,
        )
        self.metrics = MetricsLogger(
            num_params=V * H * 2, num_layers=1, num_heads=1, head_dim=H,
            seq_len=SEQ, tokens_per_step=self.loader.tokens_per_step,
            log_frequency=cfg.log_frequency, collect_system=False,
        )
        # telemetry: built from the same config the real Trainer uses
        # (disabled unless the test sets telemetry_dir), so the
        # telemetry-aware train loop binds unchanged
        from scaletorch_tpu.telemetry import Telemetry

        self.telemetry = Telemetry.from_config(cfg)
        self._tracer = self.telemetry.tracer
        self.metrics.exporter = self.telemetry.exporter
        self._last_data_fetch_s = 0.0
        self.global_step = 0
        self.tokens_seen = 0
        self.preempted = False
        self.emergency_checkpoint_saved = False
        self._loader_skew = 0
        self._saved_loader_position = None
        self._wandb_logged_step = 0
        self._pp_vpp = 1
        self._train_iter = None
        self._ckpt_mgr = None
        self._wandb = None
        # no ElasticCoordinator by default: the real train() reads
        # self.elastic to decide whether PeerLostError is recoverable
        # (tests/test_elastic.py attaches one for the elastic drills)
        self.elastic = None

    def step(self, batch=None):
        if batch is None:
            if self._train_iter is None:
                self._train_iter = iter(self.loader)
            batch = next(self._train_iter)
        self.params, self.opt_state, m = self.step_fn(
            self.params, self.opt_state, batch
        )
        self.global_step += 1
        self.tokens_seen += int(np.prod(np.shape(batch["input_ids"])))
        return m

    def close(self):
        if self._ckpt_mgr is not None:
            self._ckpt_mgr.wait()
        self.telemetry.close()


def _bind_real_trainer_methods():
    from scaletorch_tpu.trainer.trainer import Trainer

    for name in (
        "train", "save_checkpoint", "load_checkpoint",
        "_rollback_to_last_good", "_emergency_checkpoint", "_layer_storage",
        "_beat", "_span", "_stream_position", "_write_crash_report",
        "_watchdog_crash_report", "_watchdog_exit", "_live_snapshot",
        "_agree_all", "_agree_any",
        # elastic continuation (no "_elastic_rebuild_topology": its
        # absence is exactly how the mesh-free toy skips the remesh —
        # _elastic_apply_view getattr-guards it)
        "_elastic_join", "_elastic_recover", "_maybe_elastic_grow",
        "_elastic_apply_view",
    ):
        setattr(ToyTrainer, name, Trainer.__dict__[name])
    ToyTrainer.checkpoint_manager = Trainer.__dict__["checkpoint_manager"]


_bind_real_trainer_methods()


def e2e_cfg(tmp_path=None, **kw):
    defaults = dict(
        micro_batch_size=2, gradient_accumulation_steps=2,
        sequence_length=SEQ, total_train_steps=6, seed=11,
        learning_rate=1e-2, async_checkpointing=False,
        checkpoint_retry_base_delay=0.01, log_frequency=1000,
        sentinel_frequency=1,
    )
    if tmp_path is not None:
        defaults.update(checkpoint_dir=str(tmp_path), save_frequency=2,
                        crash_report_dir=str(tmp_path / "crash_reports"))
    defaults.update(kw)
    return ScaleTorchTPUArguments(**defaults)


def e2e_tokens(n=64):
    return np.random.default_rng(5).integers(
        0, V, size=(n, SEQ + 1)).astype(np.int32)


def params_finite(params):
    return all(np.isfinite(np.asarray(x)).all() for x in jax.tree.leaves(params))


class TestEndToEndFaults:
    def test_injected_nan_skip_policy_keeps_training(self, tmp_path):
        t = ToyTrainer(e2e_cfg(tmp_path, ft_nan_at_step=3,
                               divergence_policy="skip"), e2e_tokens())
        t.train()
        t.close()
        assert t.global_step == 6
        assert params_finite(t.params)
        c = t.resilience.counters()
        assert c["anomalies"] == 1.0 and c["nonfinite_losses"] == 1.0
        assert c["rollbacks"] == 0.0

    def test_injected_nan_rollback_restores_and_fast_forwards(self, tmp_path):
        cfg = e2e_cfg(tmp_path, ft_nan_at_step=3,
                      divergence_policy="rollback")
        t = ToyTrainer(cfg, e2e_tokens())
        t.train()
        t.close()
        # anomaly at step 3 -> restored the step-2 checkpoint, loader
        # fast-forwarded past the bad region, then trained to the target
        assert t.global_step == 6
        assert t.resilience.counters()["rollbacks"] == 1.0
        assert params_finite(t.params)
        # the loader really did fast-forward PAST the bad region: 6
        # optimizer steps consumed 7 stream positions (step 3's batch was
        # retired, not replayed), so the next draw is epoch-0 index 7
        from scaletorch_tpu.data.dataloader import MicroBatchDataLoader

        nxt = next(t._train_iter)
        ref_it = iter(MicroBatchDataLoader(
            e2e_tokens(), micro_batch_size=2,
            gradient_accumulation_steps=2, seed=cfg.seed))
        for _ in range(7):
            expected = next(ref_it)
        expected = next(ref_it)
        np.testing.assert_array_equal(nxt["input_ids"],
                                      expected["input_ids"])

    def test_rollback_skew_survives_checkpoint_restart(self, tmp_path):
        """A restart AFTER a rollback must not replay the retired bad
        batch: the loader skew (stream position ahead of global_step) is
        persisted in every checkpoint and restored on resume."""
        from scaletorch_tpu.data.dataloader import MicroBatchDataLoader

        cfg = e2e_cfg(tmp_path, ft_nan_at_step=3,
                      divergence_policy="rollback")
        t = ToyTrainer(cfg, e2e_tokens())
        t.train()  # rollback at 3 -> skew 1; cadence saves at 4 and 6
        t.close()
        assert t._loader_skew == 1

        t2 = ToyTrainer(e2e_cfg(tmp_path), e2e_tokens())
        assert t2.load_checkpoint()
        assert t2.global_step == 6 and t2._loader_skew == 1
        # next draw continues at stream position 7+1, not 7 — the bad
        # region stays retired across the restart
        t2.step()
        ref_it = iter(MicroBatchDataLoader(
            e2e_tokens(), micro_batch_size=2,
            gradient_accumulation_steps=2, seed=cfg.seed))
        for _ in range(8):
            next(ref_it)
        np.testing.assert_array_equal(
            next(t2._train_iter)["input_ids"],
            next(ref_it)["input_ids"],
        )
        t2.close()

    def test_second_rollback_composes_with_existing_skew(self, tmp_path):
        """A second rollback must fast-forward relative to the TRUE
        stream position (anomaly_step + existing skew), not the raw step
        number — otherwise it rewinds into already-retired data and
        replays the first bad batch."""
        from scaletorch_tpu.data.dataloader import MicroBatchDataLoader

        cfg2 = e2e_cfg(tmp_path, ft_nan_at_step=3,
                       divergence_policy="rollback", total_train_steps=6,
                       max_rollbacks=5)
        t2 = ToyTrainer(cfg2, e2e_tokens())
        t2.train()  # rollback #1: skew 1
        assert t2._loader_skew == 1
        t2.resilience.injector.nan_at_step = t2.global_step + 1
        t2.resilience.injector._nan_fired = False
        t2.train(num_steps=2)  # anomaly on the next step -> rollback #2
        assert t2.resilience.counters()["rollbacks"] == 2.0
        assert t2._loader_skew == 2  # both retired batches stay retired
        # next draw = consumed-position + skew, never a replay
        pos = t2.global_step + t2._loader_skew
        t2.step()  # consumes the draw at `pos`
        ref_it = iter(MicroBatchDataLoader(
            e2e_tokens(), micro_batch_size=2,
            gradient_accumulation_steps=2, seed=cfg2.seed))
        for _ in range(pos + 1):
            next(ref_it)
        np.testing.assert_array_equal(
            next(t2._train_iter)["input_ids"], next(ref_it)["input_ids"])
        t2.close()

    def test_injected_nan_abort_policy_raises(self, tmp_path):
        t = ToyTrainer(e2e_cfg(tmp_path, ft_nan_at_step=3,
                               divergence_policy="abort"), e2e_tokens())
        with pytest.raises(TrainingDivergedError):
            t.train()
        t.close()

    def test_sigterm_emergency_checkpoint_then_resume_auto_matches(
            self, tmp_path):
        tokens = e2e_tokens()
        # ground truth: uninterrupted 6-step run (no checkpoint cadence
        # interference — save_frequency stays on to match the recovery run)
        ref_dir = tmp_path / "ref"
        t_ref = ToyTrainer(e2e_cfg(ref_dir), tokens)
        t_ref.train()
        t_ref.close()
        ref = jax.device_get(t_ref.params)
        assert not t_ref.preempted

        # preempted run: simulated SIGTERM after step 3 -> emergency
        # checkpoint at the next step boundary + clean early return
        run_dir = tmp_path / "run"
        t1 = ToyTrainer(e2e_cfg(run_dir, ft_sigterm_at_step=3), tokens)
        t1.train()
        t1.close()
        assert t1.preempted
        assert t1.global_step == 3
        assert t1.checkpoint_manager.latest_step() == 3

        # restarted job: --resume auto semantics (train.py), same target
        t2 = ToyTrainer(e2e_cfg(run_dir), tokens)
        assert t2.load_checkpoint()
        assert t2.global_step == 3
        t2.train()  # default target is ABSOLUTE total_train_steps
        t2.close()
        assert t2.global_step == 6
        final = jax.device_get(t2.params)
        for a, b in zip(jax.tree.leaves(ref), jax.tree.leaves(final)):
            np.testing.assert_allclose(a, b, atol=1e-6)

    def test_preemption_right_after_rollback_persists_skew(self, tmp_path):
        """Preemption at the same step a rollback restored to: the
        on-disk checkpoint has the PRE-rollback loader position, so the
        emergency path must replace it (orbax silently skips same-step
        saves) — otherwise the restart replays the diverged batch."""
        cfg = e2e_cfg(tmp_path, ft_nan_at_step=3, ft_sigterm_at_step=3,
                      divergence_policy="rollback")
        t = ToyTrainer(cfg, e2e_tokens())
        t.train()
        assert t.preempted and t.global_step == 2
        assert t._loader_skew == 1 and t.emergency_checkpoint_saved
        t.close()

        t2 = ToyTrainer(e2e_cfg(tmp_path), e2e_tokens())
        assert t2.load_checkpoint()
        # the replacement checkpoint carries the post-rollback position:
        # the bad batch stays retired across the restart
        assert t2.global_step == 2 and t2._loader_skew == 1
        t2.close()

    def test_sigterm_without_checkpoint_dir_still_exits_cleanly(self):
        t = ToyTrainer(e2e_cfg(None, ft_sigterm_at_step=2), e2e_tokens())
        t.train()
        t.close()
        assert t.preempted and t.global_step == 2

    def test_first_n_save_failures_retried_without_data_loss(self, tmp_path):
        cfg = e2e_cfg(tmp_path, ft_fail_saves=2, checkpoint_retries=3)
        t = ToyTrainer(cfg, e2e_tokens())
        t.train()
        t.close()
        assert t.global_step == 6
        # both cadence saves landed despite the injected failures
        assert t.checkpoint_manager.all_steps() == [2, 4, 6]
        # and the newest checkpoint resumes cleanly
        t2 = ToyTrainer(e2e_cfg(tmp_path), e2e_tokens())
        assert t2.load_checkpoint()
        assert t2.global_step == 6 and t2.tokens_seen == t.tokens_seen
        t2.close()

    def test_save_failures_beyond_retries_never_kill_the_run(self, tmp_path):
        cfg = e2e_cfg(tmp_path, ft_fail_saves=100, checkpoint_retries=1)
        t = ToyTrainer(cfg, e2e_tokens())
        t.train()
        t.close()
        assert t.global_step == 6
        assert params_finite(t.params)

    def test_corrupt_shard_skipped_and_retired_across_restart(self, tmp_path):
        """An unreadable stream region (ft_bad_batch_at_step) is skipped
        after retries, the skip is absorbed into loader_position, and a
        restarted run keeps the region retired (no replay, no
        double-count)."""
        cfg = e2e_cfg(tmp_path, ft_bad_batch_at_step=2,
                      data_read_retries=1, data_retry_base_delay=0.001)
        t = ToyTrainer(cfg, e2e_tokens())
        t.train()
        t.close()
        assert t.global_step == 6
        # 6 optimizer steps consumed 7 stream positions (slot 2 skipped)
        assert t.loader.position == 7
        assert t.loader.skipped_positions == [2]
        assert t._loader_skew == 1

        t2 = ToyTrainer(e2e_cfg(tmp_path), e2e_tokens())
        assert t2.load_checkpoint()
        assert t2.global_step == 6 and t2._loader_skew == 1
        t2.step()
        from scaletorch_tpu.data.dataloader import MicroBatchDataLoader

        ref_it = iter(MicroBatchDataLoader(
            e2e_tokens(), micro_batch_size=2,
            gradient_accumulation_steps=2, seed=cfg.seed))
        for _ in range(8):
            next(ref_it)
        np.testing.assert_array_equal(
            next(t2._train_iter)["input_ids"], next(ref_it)["input_ids"])
        t2.close()


# ---------------------------------------------------------------------------
# Layer-storage validation (satellite: quick coverage of the error path)
# ---------------------------------------------------------------------------


class TestLayerStorageValidation:
    def test_mismatch_raises_with_remedy(self):
        from scaletorch_tpu.trainer.trainer import validate_layer_storage

        with pytest.raises(ValueError, match="convert_layer_storage"):
            validate_layer_storage(
                "model_order", "interleaved_pp2_vpp2",
                pp_engine="interleaved", pp_virtual_stages=2,
            )

    def test_match_passes(self):
        from scaletorch_tpu.trainer.trainer import validate_layer_storage

        validate_layer_storage(
            "interleaved_pp2_vpp2", "interleaved_pp2_vpp2",
            pp_engine="interleaved", pp_virtual_stages=2,
        )


# ---------------------------------------------------------------------------
# Config surface
# ---------------------------------------------------------------------------


class TestResilienceConfig:
    def test_resume_choices_validated(self):
        with pytest.raises(ValueError, match="resume"):
            ScaleTorchTPUArguments(resume="maybe")

    def test_resume_from_checkpoint_aliases_auto(self):
        cfg = ScaleTorchTPUArguments(resume_from_checkpoint=True)
        assert cfg.resume == "auto"

    def test_explicit_must_not_weakened_by_alias(self):
        cfg = ScaleTorchTPUArguments(resume_from_checkpoint=True,
                                     resume="must", checkpoint_dir="/ckpt")
        assert cfg.resume == "must"

    def test_resume_must_requires_checkpoint_dir(self):
        with pytest.raises(ValueError, match="checkpoint_dir"):
            ScaleTorchTPUArguments(resume="must")

    def test_divergence_policy_validated(self):
        with pytest.raises(ValueError, match="divergence_policy"):
            ScaleTorchTPUArguments(divergence_policy="panic")

    def test_negative_knobs_rejected(self):
        with pytest.raises(ValueError, match="ft_fail_saves"):
            ScaleTorchTPUArguments(ft_fail_saves=-1)
        with pytest.raises(ValueError, match="checkpoint_retries"):
            ScaleTorchTPUArguments(checkpoint_retries=-1)

    def test_spike_factor_at_or_below_one_rejected(self):
        # (0, 1] would flag nearly every healthy step as a spike
        with pytest.raises(ValueError, match="loss_spike_factor"):
            ScaleTorchTPUArguments(loss_spike_factor=0.5)
        with pytest.raises(ValueError, match="loss_spike_factor"):
            ScaleTorchTPUArguments(loss_spike_factor=-2.0)
        ScaleTorchTPUArguments(loss_spike_factor=2.0)  # valid
        ScaleTorchTPUArguments(loss_spike_factor=0.0)  # off

    def test_ema_beta_out_of_range_rejected(self):
        with pytest.raises(ValueError, match="loss_ema_beta"):
            ScaleTorchTPUArguments(loss_ema_beta=1.5)
        with pytest.raises(ValueError, match="loss_ema_beta"):
            ScaleTorchTPUArguments(loss_ema_beta=-0.1)
