"""Multi-host resilience (scaletorch_tpu/resilience_distributed.py).

The acceptance surface of the coordinated layer, exercised hermetically
in one process: N simulated hosts run the REAL protocol (the same
``CoordinatedResilience`` / ``CheckpointManager`` / ``Trainer.train``
code paths production uses) over a barrier-backed ``FakeBus`` whose
``all_gather``/``broadcast`` keep the ``dist.py`` object-collective
contracts. Each simulated host is one thread; a host that deadlocks or
desyncs breaks the barrier and fails the test instead of hanging it.

Covered here:
  * one-host SIGTERM → a collective stop + emergency checkpoint at the
    SAME step on every host (the PR-1 ``process_count() == 1`` gate is
    gone — asserted against the source);
  * a sentinel rollback decision identical on all hosts, including when
    only one host observes the anomaly;
  * host-disagreement: a drifted host obeys host 0's broadcast;
  * abort raised in lockstep on every host;
  * coordinated checkpoint save retries / fleet-wide restore fallback /
    symmetric async→sync degradation;
  * post-save integrity verification (opt-in) retiring a mangled step;
  * the hang watchdog: fires within the timeout, dumps thread stacks +
    ring buffer to a crash report, exits with the documented code 43 —
    unit and end-to-end (FaultInjector stall) variants.
"""

import glob
import inspect
import json
import threading
import time
from functools import partial

import numpy as np
import pytest

from scaletorch_tpu.resilience import (
    DivergenceSentinel,
    FaultInjector,
    PreemptionHandler,
    ResilienceManager,
    TrainingDivergedError,
)
from scaletorch_tpu.resilience_distributed import (
    DIVERGED_EXIT_CODE,
    WATCHDOG_EXIT_CODE,
    CoordinatedResilience,
    DecisionBus,
    HangWatchdog,
    config_fingerprint,
    dump_thread_stacks,
    write_crash_report,
)
from tests.test_resilience import ToyTrainer, e2e_cfg, e2e_tokens

pytestmark = pytest.mark.multihost


# ---------------------------------------------------------------------------
# Fake N-host collective bus
# ---------------------------------------------------------------------------


class FakeBus:
    """Barrier-backed object collectives with the dist.py contracts,
    shared by N host threads. A host that stops participating (crash,
    desync) breaks the barrier within ``timeout`` and every peer raises
    instead of hanging the test suite."""

    def __init__(self, n: int, timeout: float = 30.0):
        self.n = n
        self.timeout = timeout
        self._barrier = threading.Barrier(n)
        self._slots = [None] * n

    def host(self, i: int) -> DecisionBus:
        return DecisionBus(
            num_processes=self.n,
            process_index=i,
            all_gather=partial(self._all_gather, i),
            broadcast=partial(self._broadcast, i),
        )

    def _all_gather(self, rank: int, obj):
        self._slots[rank] = obj
        self._barrier.wait(self.timeout)
        out = list(self._slots)
        self._barrier.wait(self.timeout)  # slots stable until all read
        return out

    def _broadcast(self, rank: int, objs: list, src: int = 0) -> list:
        gathered = self._all_gather(
            rank, list(objs) if rank == src else None)
        objs[:] = gathered[src]
        return objs


def run_hosts(n, fn, timeout=60.0):
    """Run ``fn(host_index, DecisionBus)`` on N threads; returns
    (results, errors) indexed by host."""
    bus = FakeBus(n)
    results, errors = [None] * n, [None] * n

    def worker(i):
        try:
            results[i] = fn(i, bus.host(i))
        except Exception as exc:  # noqa: BLE001 — surfaced via `errors`
            errors[i] = exc

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout)
    assert not any(t.is_alive() for t in threads), \
        "a simulated host wedged (collective desync?)"
    return results, errors


def make_manager(policy="skip", **sentinel_kw):
    return ResilienceManager(
        sentinel=DivergenceSentinel(policy=policy, **sentinel_kw),
        injector=FaultInjector(),
        sentinel_frequency=1,
    )


# ---------------------------------------------------------------------------
# Decision protocol (CoordinatedResilience directly)
# ---------------------------------------------------------------------------


class TestCoordinatedDecisions:
    def test_one_host_stop_flag_stops_everyone(self):
        def host(i, bus):
            mgr = make_manager()
            mgr.preemption = PreemptionHandler()
            if i == 2:
                mgr.preemption.trigger()
            coord = CoordinatedResilience(mgr, bus=bus)
            return coord.should_stop()

        results, errors = run_hosts(4, host)
        assert errors == [None] * 4
        assert results == [True] * 4

    def test_healthy_step_ok_everywhere(self):
        def host(i, bus):
            coord = CoordinatedResilience(make_manager(), bus=bus)
            m, action = coord.after_step(1, {"loss": 2.0})
            return action, coord.manager.sentinel.ema

        results, errors = run_hosts(4, host)
        assert errors == [None] * 4
        # identical action AND identical sentinel state fleet-wide
        assert all(r == ("ok", 2.0) for r in results)

    def test_one_host_nan_skips_fleet_wide(self):
        def host(i, bus):
            coord = CoordinatedResilience(make_manager(), bus=bus)
            coord.after_step(1, {"loss": 2.0})
            loss = float("nan") if i == 1 else 2.0
            _, action = coord.after_step(2, {"loss": loss})
            return action, coord.manager.sentinel.total_anomalies

        results, errors = run_hosts(4, host)
        assert errors == [None] * 4
        # every host counts the agreed anomaly, not just the observer
        assert all(r == ("skip", 1) for r in results)

    def test_drifted_host_obeys_host0_broadcast(self):
        # host 1's EMA has drifted (simulated partial restart): its local
        # verdict for the same loss differs, but the broadcast wins
        def host(i, bus):
            mgr = make_manager(policy="skip", spike_factor=2.0)
            mgr.sentinel.ema = 1.0 if i == 0 else 100.0
            coord = CoordinatedResilience(mgr, bus=bus)
            _, action = coord.after_step(5, {"loss": 3.0})
            return action

        results, errors = run_hosts(2, host)
        assert errors == [None] * 2
        # host 0 sees 3.0 > 2x its EMA of 1.0 -> skip; host 1 would have
        # said ok but must obey
        assert results == ["skip", "skip"]

    def test_abort_raises_on_every_host(self):
        def host(i, bus):
            coord = CoordinatedResilience(
                make_manager(policy="abort"), bus=bus)
            coord.after_step(1, {"loss": 1.0})
            loss = float("nan") if i == 3 else 1.0
            coord.after_step(2, {"loss": loss})

        _, errors = run_hosts(4, host)
        assert all(isinstance(e, TrainingDivergedError) for e in errors)

    def test_partial_rollback_restore_raises_everywhere(self):
        # 2 of 4 hosts restore, 2 do not -> params now differ across the
        # fleet; continuing would train a franken-model, so every host
        # must raise the identical error
        def host(i, bus):
            coord = CoordinatedResilience(
                make_manager(policy="rollback"), bus=bus)
            coord.after_step(1, {"loss": 1.0})
            coord.after_step(2, {"loss": float("nan")},
                             rollback=lambda: i < 2)

        _, errors = run_hosts(4, host)
        assert all(isinstance(e, TrainingDivergedError) for e in errors)
        assert all("diverged across hosts" in str(e) for e in errors)

    def test_no_rollback_anywhere_downgrades_to_skip(self):
        def host(i, bus):
            coord = CoordinatedResilience(
                make_manager(policy="rollback"), bus=bus)
            coord.after_step(1, {"loss": 1.0})
            _, action = coord.after_step(2, {"loss": float("nan")},
                                         rollback=lambda: False)
            return action

        results, errors = run_hosts(3, host)
        assert errors == [None] * 3
        assert results == ["skip"] * 3

    def test_stream_position_desync_aborts_fleet_wide(self):
        # a host-local skip of an unreadable region advanced ONE host's
        # loader past its peers: silent mismatched-batch training must
        # become a loud lockstep abort
        def host(i, bus):
            coord = CoordinatedResilience(make_manager(), bus=bus)
            coord.after_step(1, {"loss": 2.0}, position=1)
            coord.after_step(2, {"loss": 2.0},
                             position=3 if i == 2 else 2)

        _, errors = run_hosts(4, host)
        assert all(isinstance(e, TrainingDivergedError) for e in errors)
        assert all("desynced" in str(e) for e in errors)

    def test_agreeing_positions_pass(self):
        def host(i, bus):
            coord = CoordinatedResilience(make_manager(), bus=bus)
            _, action = coord.after_step(1, {"loss": 2.0}, position=5)
            return action

        results, errors = run_hosts(3, host)
        assert errors == [None] * 3 and results == ["ok"] * 3

    def test_verify_agreement_catches_divergent_steps(self):
        def host(i, bus):
            coord = CoordinatedResilience(make_manager(), bus=bus)
            coord.verify_agreement("step", 7 if i != 1 else 8)

        _, errors = run_hosts(3, host)
        assert all(isinstance(e, TrainingDivergedError) for e in errors)

    def test_single_process_passthrough(self):
        mgr = make_manager()
        coord = CoordinatedResilience(mgr)  # no bus, 1 process
        assert not coord.coordinated
        m, action = coord.after_step(1, {"loss": 2.0})
        assert action == "ok"
        assert coord.should_stop() is False


# ---------------------------------------------------------------------------
# End-to-end: the REAL Trainer.train loop on 4 simulated hosts
# ---------------------------------------------------------------------------


def _multihost_toy(i, bus, tmp_path, **cfg_kw):
    cfg = e2e_cfg(tmp_path / f"host{i}", **cfg_kw)
    t = ToyTrainer(cfg, e2e_tokens())
    t.coordinator = CoordinatedResilience(t.resilience, bus=bus)
    inj = t.resilience.injector
    inj.host_index = i
    # route the injected SIGTERM to THIS host's handler (a real os.kill
    # would stop every simulated host at once and prove nothing)
    inj.deliver_signal = (
        lambda s, r=t.resilience: r.preemption.trigger(s)
        if r.preemption is not None else None
    )
    return t


class TestMultiHostTrainer:
    def test_one_host_sigterm_collective_emergency_save(self, tmp_path):
        """SIGTERM on exactly one host -> every host executes the
        emergency-checkpoint decision at the SAME step."""

        def host(i, bus):
            t = _multihost_toy(i, bus, tmp_path,
                               ft_sigterm_at_step=3, ft_sigterm_host=2)
            t.train()
            t.close()
            return (t.preempted, t.global_step,
                    t.emergency_checkpoint_saved,
                    t.checkpoint_manager.latest_step())

        results, errors = run_hosts(4, host)
        assert errors == [None] * 4
        assert results == [(True, 3, True, 3)] * 4

    def test_rollback_decision_identical_on_all_hosts(self, tmp_path):
        """Anomaly observed on ONE host -> the rollback is executed by
        every host; sentinel counters and loader skew agree fleet-wide
        (no host acts unilaterally)."""

        def host(i, bus):
            t = _multihost_toy(
                i, bus, tmp_path, divergence_policy="rollback",
                ft_nan_at_step=3 if i == 1 else 0)
            t.train()
            t.close()
            return (t.global_step,
                    t.resilience.counters()["rollbacks"],
                    t._loader_skew,
                    t.checkpoint_manager.all_steps())

        results, errors = run_hosts(4, host)
        assert errors == [None] * 4
        assert results == [(6, 1.0, 1, [2, 4, 6])] * 4

    def test_abort_is_lockstep_and_leaves_crash_reports(self, tmp_path):
        def host(i, bus):
            t = _multihost_toy(
                i, bus, tmp_path, divergence_policy="abort",
                ft_nan_at_step=3 if i == 0 else 0)
            try:
                t.train()
            finally:
                t.close()

        _, errors = run_hosts(4, host)
        assert all(isinstance(e, TrainingDivergedError) for e in errors)
        reports = sorted(glob.glob(
            str(tmp_path / "host*" / "crash_reports" / "crash_report_*")))
        assert len(reports) == 4
        body = json.loads(open(reports[0]).read())
        assert body["step"] == 3
        assert body["counters"]["nonfinite_losses"] == 1.0
        assert body["config_fingerprint"]["divergence_policy"] == "abort"

    def test_rollback_agrees_before_any_host_returns_early(self, tmp_path):
        """One host's directory listing shows no checkpoint (list-after-
        write lag / racing retention sweep): the fleet must agree to
        downgrade BEFORE anyone enters the restore collectives — a
        unilateral early return would leave its peers wedged in a
        broadcast no one answers."""
        from scaletorch_tpu.utils.checkpoint import CheckpointManager

        def host(i, bus):
            t = _multihost_toy(i, bus, tmp_path)
            if i == 0:
                # seed ONLY host 0's directory, via a bus-less manager so
                # the setup itself is not a collective
                setup = CheckpointManager(str(tmp_path / "host0"),
                                          async_save=False)
                setup.save(1, params={"w": np.ones(2, np.float32)},
                           opt_state={"m": np.zeros(2, np.float32)})
                setup.close()
            return t._rollback_to_last_good(2)

        results, errors = run_hosts(2, host)
        assert errors == [None, None]
        assert results == [False, False]  # agreed: nobody rolls back

    def test_stop_flag_rides_the_step_decision(self, tmp_path):
        """The boundary stop poll reuses the previous after_step gather
        (one collective round per step): a SIGTERM fired before step 3's
        decision stops every host at step 3, not later."""

        def host(i, bus):
            t = _multihost_toy(i, bus, tmp_path,
                               ft_sigterm_at_step=3, ft_sigterm_host=0)
            t.train()
            t.close()
            return t.preempted, t.global_step

        results, errors = run_hosts(2, host)
        assert errors == [None, None]
        assert results == [(True, 3), (True, 3)]

    def test_train_has_no_single_host_preemption_gate(self):
        from scaletorch_tpu.trainer.trainer import Trainer

        src = inspect.getsource(Trainer.train)
        assert "process_count() == 1" not in src

    def test_env_overrides_route_through_registry(self, monkeypatch):
        from scaletorch_tpu.resilience_distributed import (
            coordinate_from_config,
            hang_timeout_from_config,
        )

        cfg = e2e_cfg(None, ft_hang_timeout=1.0)
        assert hang_timeout_from_config(cfg) == 1.0
        monkeypatch.setenv("SCALETORCH_TPU_FT_HANG_TIMEOUT", "2.5")
        assert hang_timeout_from_config(cfg) == 2.5
        assert coordinate_from_config(cfg) is True
        monkeypatch.setenv("SCALETORCH_TPU_FT_COORDINATE", "0")
        assert coordinate_from_config(cfg) is False  # present-wins


# ---------------------------------------------------------------------------
# Coordinated checkpoint manager
# ---------------------------------------------------------------------------


def _tree(x=1.0):
    return {"w": np.full((2, 3), x, dtype=np.float32)}


def _make_cm(tmp_path, i, bus, **kw):
    from scaletorch_tpu.utils.checkpoint import CheckpointManager

    kw.setdefault("retry_base_delay", 0.01)
    kw.setdefault("async_save", False)
    return CheckpointManager(str(tmp_path / f"host{i}"), decision_bus=bus,
                             **kw)


class TestCoordinatedCheckpoints:
    def test_one_host_failure_retried_in_lockstep(self, tmp_path):
        def host(i, bus):
            inj = FaultInjector(fail_saves=1 if i == 0 else 0)
            cm = _make_cm(tmp_path, i, bus, retries=3, fault_injector=inj)
            ok = cm.save(1, params=_tree(), opt_state=_tree())
            cm.wait()
            return ok, cm.all_steps()

        results, errors = run_hosts(2, host)
        assert errors == [None] * 2
        assert results == [(True, [1])] * 2

    def test_exhausted_retries_fail_symmetrically_without_raising(
            self, tmp_path):
        def host(i, bus):
            inj = FaultInjector(fail_saves=100 if i == 1 else 0)
            cm = _make_cm(tmp_path, i, bus, retries=1, fault_injector=inj)
            return cm.save(1, params=_tree(), opt_state=_tree())

        results, errors = run_hosts(2, host)
        assert errors == [None] * 2
        assert results == [False, False]

    def test_mixed_saved_skipped_retries_to_convergence(self, tmp_path):
        """One host's directory view already lists the step (orbax's
        should_save silently no-ops -> saved=False) while its peer saves
        (True): the agreed outcome must not diverge — the stale copy is
        retired and the retry converges on all-saved."""
        from scaletorch_tpu.utils.checkpoint import CheckpointManager

        def host(i, bus):
            if i == 0:  # pre-existing step 1 in host 0's view only
                setup = CheckpointManager(str(tmp_path / "host0"),
                                          async_save=False)
                setup.save(1, params=_tree(0.0), opt_state=_tree())
                setup.close()
            cm = _make_cm(tmp_path, i, bus, retries=2)
            ok = cm.save(1, params=_tree(), opt_state=_tree())
            cm.wait()
            return ok

        results, errors = run_hosts(2, host)
        assert errors == [None] * 2
        assert results == [True, True]

    def test_corrupt_step_falls_back_fleet_wide(self, tmp_path):
        import shutil

        def host(i, bus):
            cm = _make_cm(tmp_path, i, bus, retries=0)
            for step in (1, 2):
                assert cm.save(step, params=_tree(step), opt_state=_tree())
            cm.wait()
            if i == 0:  # corrupt ONLY host 0's newest step
                victim = next(
                    p for p in (tmp_path / "host0" / "2").iterdir()
                    if "param" in p.name)
                shutil.rmtree(victim)
            out = cm.load_latest(params=_tree(), opt_state=_tree())
            return out["step"] if out else None

        results, errors = run_hosts(2, host)
        assert errors == [None] * 2
        # host 1's step 2 restores fine locally, but the fleet must land
        # on ONE step — the newest readable everywhere
        assert results == [1, 1]

    def test_wait_failure_degrades_every_host_to_sync(self, tmp_path):
        def host(i, bus):
            cm = _make_cm(tmp_path, i, bus, retries=1, async_save=True)
            if i == 0:
                cm._mgr.wait_until_finished = lambda: (_ for _ in ()).throw(
                    RuntimeError("pool dead"))
            cm.wait()
            return cm._async

        results, errors = run_hosts(2, host)
        assert errors == [None] * 2
        assert results == [False, False]


# ---------------------------------------------------------------------------
# Post-save integrity verification (opt-in)
# ---------------------------------------------------------------------------


class TestCheckpointVerification:
    def _cm(self, tmp_path, **kw):
        from scaletorch_tpu.utils.checkpoint import CheckpointManager

        kw.setdefault("retry_base_delay", 0.01)
        return CheckpointManager(str(tmp_path), async_save=False,
                                 verify=True, **kw)

    def test_clean_save_verifies(self, tmp_path):
        cm = self._cm(tmp_path)
        assert cm.save(1, params=_tree(), opt_state=_tree())
        assert cm.all_steps() == [1]

    def test_metadata_mismatch_retires_the_step(self, tmp_path):
        cm = self._cm(tmp_path)
        # a torn write: the read-back metadata is missing the params item
        cm._mgr.item_metadata = lambda step: type(
            "MD", (), {"params": None, "opt_state": None})()
        assert cm.save(1, params=_tree(), opt_state=_tree()) is False
        assert cm.all_steps() == []  # retired via the unreadable path

    def test_verify_mismatch_describes_shape_drift(self, tmp_path):
        cm = self._cm(tmp_path)
        assert cm.save(1, params=_tree(), opt_state=_tree())
        other = {"w": np.zeros((4, 4), dtype=np.float32)}
        msg = cm._verify_mismatch(1, other, _tree())
        assert msg is not None and "shape" in msg
        assert cm._verify_mismatch(1, _tree(), _tree()) is None


# ---------------------------------------------------------------------------
# Hang watchdog
# ---------------------------------------------------------------------------


class TestHangWatchdog:
    def test_fires_dumps_and_exits_with_documented_code(self, tmp_path):
        exits, reports = [], []

        def report(info):
            path = write_crash_report(
                info["reason"], info["step"],
                directory=str(tmp_path),
                thread_stacks=info["thread_stacks"],
                monitor_records=[{"step": 1, "host_cpu_percent": 1.0}],
            )
            reports.append(path)
            return path

        wd = HangWatchdog(timeout=0.2, poll_interval=0.05,
                          crash_report=report, exit_fn=exits.append)
        with wd:
            wd.beat(1, "step_dispatch")
            time.sleep(0.8)
        assert wd.fired
        assert exits == [WATCHDOG_EXIT_CODE] and WATCHDOG_EXIT_CODE == 43
        body = json.loads(open(reports[0]).read())
        assert "step_dispatch" in body["reason"]
        assert body["monitor_records"]  # ring buffer rode along
        # the stack dump names this (the main) thread and a real frame
        assert any("MainThread" in k for k in body["thread_stacks"])
        assert "time.sleep" in "".join(body["thread_stacks"].values()) \
            or "test_resilience_distributed" in \
            "".join(body["thread_stacks"].values())

    def test_beats_keep_it_quiet(self):
        exits = []
        wd = HangWatchdog(timeout=0.3, poll_interval=0.05,
                          exit_fn=exits.append)
        with wd:
            for _ in range(8):
                time.sleep(0.07)
                wd.beat(2, "data_fetch")
        assert not wd.fired and exits == []

    def test_rejects_nonpositive_timeout(self):
        with pytest.raises(ValueError, match="timeout"):
            HangWatchdog(timeout=0.0)

    def test_injected_hang_trips_watchdog_end_to_end(self, tmp_path):
        """Acceptance: a FaultInjector stall at step k trips the watchdog
        within the configured timeout, writes a crash report containing
        thread stacks + ring buffer, and requests the documented exit
        code — on the REAL Trainer.train loop."""
        cfg = e2e_cfg(tmp_path, total_train_steps=4,
                      ft_hang_at_step=2, ft_hang_seconds=1.2,
                      ft_hang_timeout=0.3)
        t = ToyTrainer(cfg, e2e_tokens())
        codes = []
        t._watchdog_exit = codes.append  # record instead of os._exit
        t.step()  # compile the jit step OUTSIDE the watchdog window
        t.train()
        t.close()
        # the injected stall outlived the timeout -> watchdog fired with
        # the documented code; the (recorded, not executed) exit lets the
        # loop finish its remaining steps hermetically
        assert codes == [WATCHDOG_EXIT_CODE]
        assert t.global_step == 4
        reports = glob.glob(
            str(tmp_path / "crash_reports" / "crash_report_step2*"))
        assert len(reports) == 1
        body = json.loads(open(reports[0]).read())
        assert "hang watchdog" in body["reason"]
        assert body["thread_stacks"]
        assert "monitor_records" in body
        assert body["config_fingerprint"]["total_train_steps"] == 4

    def test_watchdog_disarmed_after_train(self, tmp_path):
        cfg = e2e_cfg(tmp_path, total_train_steps=2, ft_hang_timeout=5.0)
        t = ToyTrainer(cfg, e2e_tokens())
        t.train()
        t.close()
        assert t._watchdog is None  # stopped + cleared in the finally


# ---------------------------------------------------------------------------
# Crash reports
# ---------------------------------------------------------------------------


class TestCrashReports:
    def test_writer_contract(self, tmp_path):
        path = write_crash_report(
            "sentinel abort", 17, directory=str(tmp_path),
            counters={"anomalies": 2.0},
            last_metrics=[{"step": 17, "loss": 9.9}],
            monitor_records=[{"step": 16, "host_mem_percent": 40.0}],
        )
        assert path.endswith("crash_report_step17.json")
        body = json.loads(open(path).read())
        assert body["reason"] == "sentinel abort"
        assert body["counters"]["anomalies"] == 2.0
        assert body["last_metrics"][0]["loss"] == 9.9

    def test_nonzero_process_gets_suffixed_file(self, tmp_path):
        path = write_crash_report("x", 3, directory=str(tmp_path),
                                  process_index=2)
        assert path.endswith("crash_report_step3_proc2.json")

    def test_unwritable_directory_never_raises(self):
        assert write_crash_report(
            "x", 1, directory="/proc/definitely/not/writable") == ""

    def test_fingerprint_is_stable_and_carries_identity(self):
        cfg = e2e_cfg(None)
        a, b = config_fingerprint(cfg), config_fingerprint(cfg)
        assert a == b and len(a["sha256"]) == 16
        assert a["seed"] == cfg.seed

    def test_rollback_budget_exhaustion_writes_report(self, tmp_path):
        cfg = e2e_cfg(tmp_path, divergence_policy="rollback",
                      max_rollbacks=1, ft_nan_at_step=3)
        t = ToyTrainer(cfg, e2e_tokens())
        t.train()  # rollback #1 consumes the budget
        t.resilience.injector.nan_at_step = t.global_step + 1
        t.resilience.injector._nan_fired = False
        with pytest.raises(TrainingDivergedError, match="rollback"):
            t.train(num_steps=2)
        t.close()
        reports = glob.glob(str(tmp_path / "crash_reports" / "*.json"))
        assert len(reports) == 1
        assert "rollback" in json.loads(open(reports[0]).read())["reason"]

    def test_thread_stack_dump_sees_all_threads(self):
        stacks = dump_thread_stacks()
        assert any("MainThread" in name for name in stacks)

    def test_exit_codes_documented_and_distinct(self):
        assert DIVERGED_EXIT_CODE == 42 and WATCHDOG_EXIT_CODE == 43
