"""Unified telemetry (scaletorch_tpu/telemetry/): spans, profiling,
stragglers, export — unit + hermetic end-to-end.

The e2e layer reuses the test_resilience ``ToyTrainer`` discipline: the
REAL ``Trainer.train`` loop (telemetry hooks and all) over a tiny
mesh-free step, so the instrumentation under test is the production
instrumentation. Acceptance surface (ISSUE 9):

  * the Chrome-trace JSON loads (valid trace-event schema) and contains
    data_fetch / step_dispatch / checkpoint_save spans;
  * the JSONL stream is schema-valid with one record per logged step;
  * an injected slow step (--ft_slow_step_at_step) arms EXACTLY ONE
    bounded profiler window under --telemetry_dir;
  * a threaded 4-host FakeBus run with one delayed host surfaces that
    host's index in the straggler report;
  * with telemetry disabled, the instrumented loop's per-step overhead
    is within noise of a no-telemetry run (asserted loosely).
"""

import json
import logging
import os
import signal
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from scaletorch_tpu.telemetry import (
    SCHEMA_VERSION,
    AnomalyProfiler,
    LiveSnapshotter,
    PrometheusEndpoint,
    SlowStepDetector,
    SpanTracer,
    StragglerDetector,
    Telemetry,
    TelemetryExporter,
    load_trace,
    parse_profile_steps,
)
from scaletorch_tpu.telemetry.export import read_jsonl, render_prometheus
from tests.test_resilience import ToyTrainer, e2e_cfg, e2e_tokens


# ---------------------------------------------------------------------------
# SpanTracer
# ---------------------------------------------------------------------------


class TestSpanTracer:
    def test_trace_file_is_valid_chrome_trace(self, tmp_path):
        path = str(tmp_path / "t.trace.json")
        tr = SpanTracer(path, process_index=3)
        with tr.span("data_fetch", step=1):
            pass
        tr.instant("note", detail="x")
        tr.counter("straggler_flags", 2)
        tr.close()
        events = json.load(open(path))  # valid JSON after close()
        assert isinstance(events, list)
        by_name = {e["name"]: e for e in events}
        span = by_name["data_fetch"]
        # trace-event schema: complete events need ph/ts/dur/pid/tid
        assert span["ph"] == "X" and span["dur"] >= 0
        assert span["pid"] == 3 and "tid" in span and "ts" in span
        assert span["args"] == {"step": 1}
        assert by_name["note"]["ph"] == "i"
        assert by_name["straggler_flags"]["ph"] == "C"
        assert by_name["straggler_flags"]["args"]["value"] == 2
        assert by_name["process_name"]["ph"] == "M"

    def test_phase_track_closes_previous_and_survives_crash(self, tmp_path):
        path = str(tmp_path / "t.trace.json")
        tr = SpanTracer(path)
        tr.phase("step_boundary", step=0)
        tr.phase("data_fetch", step=0)
        tr.phase("step_dispatch", step=0)
        tr.flush()
        # no close(): the unterminated file must still load (the
        # crashed-run form Perfetto tolerates)
        events = load_trace(path)
        names = [e["name"] for e in events if e.get("ph") == "X"]
        assert names == ["step_boundary", "data_fetch"]  # dispatch open
        tr.close()
        names = [e["name"] for e in json.load(open(path))
                 if e.get("ph") == "X"]
        assert names == ["step_boundary", "data_fetch", "step_dispatch"]

    def test_tail_keeps_newest_and_is_capped(self, tmp_path):
        tr = SpanTracer(str(tmp_path / "t.trace.json"), tail_size=4)
        for i in range(10):
            with tr.span(f"s{i}"):
                pass
        tail = tr.tail()
        assert [e["name"] for e in tail] == ["s6", "s7", "s8", "s9"]
        assert [e["name"] for e in tr.tail(2)] == ["s8", "s9"]
        tr.close()

    def test_max_events_caps_file_but_not_tail(self, tmp_path):
        path = str(tmp_path / "t.trace.json")
        tr = SpanTracer(path, max_events=3, tail_size=16)
        for i in range(6):
            with tr.span(f"s{i}"):
                pass
        tr.close()
        assert tr.events_dropped == 3
        events = json.load(open(path))
        file_names = [e["name"] for e in events if e.get("ph") == "X"]
        assert file_names == ["s0", "s1", "s2"]
        # the drop count is recorded in metadata so a reader knows the
        # timeline is incomplete
        [drop] = [e for e in events if e["name"] == "events_dropped"]
        assert drop["args"]["count"] == 3
        # the tail keeps the NEWEST — crash reports want the end
        assert [e["name"] for e in tr.tail(3)] == ["s3", "s4", "s5"]

    def test_lock_reentrant_from_signal_handler_context(self):
        # A SIGUSR1 live-snapshot handler runs on the main thread and
        # reads tail() — which must not deadlock when the signal landed
        # while that same thread held the lock inside _emit.
        tr = SpanTracer(path=None)
        tr.instant("x")
        with tr._lock:  # simulate: handler fires mid-_emit
            assert tr._lock.acquire(blocking=False), (
                "tracer lock must be reentrant (SIGUSR1 handler reads "
                "tail() on the thread that may hold it)")
            tr._lock.release()
            assert tr.tail()[-1]["name"] == "x"

    def test_memory_only_tracer_writes_no_file(self, tmp_path):
        tr = SpanTracer(None)
        with tr.span("x"):
            pass
        assert len(tr.tail()) == 1
        tr.close()
        assert list(tmp_path.iterdir()) == []

    def test_disabled_tracer_records_nothing(self):
        tr = SpanTracer(None, enabled=False)
        with tr.span("x"):
            pass
        tr.phase("a")
        tr.instant("b")
        tr.counter("c", 1)
        assert tr.tail() == []

    def test_close_is_idempotent_and_disables(self, tmp_path):
        path = str(tmp_path / "t.trace.json")
        tr = SpanTracer(path)
        with tr.span("x"):
            pass
        tr.close()
        tr.close()
        with tr.span("y"):
            pass
        assert [e["name"] for e in json.load(open(path))
                if e.get("ph") == "X"] == ["x"]


# ---------------------------------------------------------------------------
# Export: JSONL + Prometheus
# ---------------------------------------------------------------------------


class TestExport:
    def test_jsonl_schema_envelope(self, tmp_path):
        path = str(tmp_path / "e.jsonl")
        ex = TelemetryExporter(path, process_index=2)
        ex.emit("train_step", {"step": 1, "loss": 2.5})
        ex.emit("engine_metrics", {"tokens_per_second": 10.0})
        ex.close()
        lines = read_jsonl(path)
        assert len(lines) == 2
        for line in lines:
            assert line["v"] == SCHEMA_VERSION
            assert line["proc"] == 2
            assert line["time"] > 0
        assert lines[0]["kind"] == "train_step" and lines[0]["step"] == 1
        assert lines[1]["kind"] == "engine_metrics"

    def test_non_serialisable_values_reprd_not_dropped(self, tmp_path):
        path = str(tmp_path / "e.jsonl")
        ex = TelemetryExporter(path)
        ex.emit("train_step", {"weird": object()})
        ex.close()
        assert "object object" in read_jsonl(path)[0]["weird"]

    def test_render_prometheus_text_format(self):
        body = render_prometheus(
            {"tokens/s": 5.0, "occupancy": 0.5, "label": "skip-me"})
        assert "# TYPE scaletorch_occupancy gauge" in body
        assert "scaletorch_occupancy 0.5" in body
        assert "scaletorch_tokens_s 5.0" in body  # name sanitised
        assert "skip-me" not in body              # non-numeric skipped
        assert body.endswith("\n")

    def test_prometheus_endpoint_serves_metrics(self):
        with PrometheusEndpoint(lambda: {"queue_depth": 3}) as pe:
            url = f"http://127.0.0.1:{pe.port}/metrics"
            body = urllib.request.urlopen(url).read().decode()
            assert "scaletorch_queue_depth 3.0" in body
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{pe.port}/other")

    def test_prometheus_scrape_error_returns_500(self):
        def broken():
            raise RuntimeError("boom")

        with PrometheusEndpoint(broken) as pe:
            with pytest.raises(urllib.error.HTTPError) as exc_info:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{pe.port}/metrics")
            assert exc_info.value.code == 500


# ---------------------------------------------------------------------------
# Slow-step detector + anomaly profiler (fake backend)
# ---------------------------------------------------------------------------


class FakeBackend:
    def __init__(self, fail_start=False):
        self.calls = []
        self.fail_start = fail_start

    def start(self, log_dir):
        if self.fail_start:
            raise RuntimeError("no profiler here")
        self.calls.append(("start", log_dir))

    def stop(self):
        self.calls.append(("stop", None))


class TestSlowStepDetector:
    def test_warmup_discarded_entirely(self):
        d = SlowStepDetector(3.0, warmup_steps=2)
        assert not d.observe(10.0)    # cold compile: discarded
        assert not d.observe(100.0)   # still warmup: discarded
        assert d.ema is None          # the compile never seeds the EMA
        assert not d.observe(1.0)     # seeds the baseline
        assert d.ema == 1.0 and d.spikes == 0

    def test_spike_detected_and_never_feeds_ema(self):
        d = SlowStepDetector(2.0, ema_beta=0.5, warmup_steps=1)
        d.observe(99.0)              # discarded (compile)
        d.observe(1.0)               # seeds the EMA
        assert d.observe(10.0)       # 10 > 2 * 1.0
        assert d.ema == 1.0          # anomaly excluded from the baseline
        assert not d.observe(1.2)
        assert d.ema == pytest.approx(1.1)

    def test_validation(self):
        with pytest.raises(ValueError, match="spike_factor"):
            SlowStepDetector(1.0)
        with pytest.raises(ValueError, match="ema_beta"):
            SlowStepDetector(2.0, ema_beta=1.0)


class TestAnomalyProfiler:
    def test_slow_step_arms_exactly_one_bounded_window(self, tmp_path):
        be = FakeBackend()
        p = AnomalyProfiler(str(tmp_path), window_steps=2,
                            spike_factor=3.0, max_captures=1, backend=be)
        times = [0.01, 0.01, 0.01, 0.5, 0.01, 0.01, 0.5, 0.01, 0.01]
        for step, t in enumerate(times, start=1):
            p.before_step(step)
            p.after_step(step, t)
        p.close()
        # one window despite TWO slow steps: max_captures bounds it
        assert len(p.captures) == 1
        cap = p.captures[0]
        assert cap["trigger"] == "slow_step"
        assert (cap["start_step"], cap["stop_step"]) == (5, 7)  # bounded
        assert be.calls == [
            ("start", cap["dir"]), ("stop", None)]

    def test_manual_window_covers_start_to_stop(self, tmp_path):
        be = FakeBackend()
        p = AnomalyProfiler(str(tmp_path), profile_steps=(3, 5), backend=be)
        for step in range(1, 8):
            p.before_step(step)
            p.after_step(step, 0.01)
        p.close()
        assert len(p.captures) == 1
        assert p.captures[0]["trigger"] == "manual"
        assert (p.captures[0]["start_step"],
                p.captures[0]["stop_step"]) == (3, 5)

    def test_manual_window_opens_late_on_resumed_run(self, tmp_path):
        # --resume past the start step: the remainder of the window is
        # still captured (>= not ==)
        be = FakeBackend()
        p = AnomalyProfiler(str(tmp_path), profile_steps=(3, 6), backend=be)
        for step in range(5, 9):
            p.before_step(step)
            p.after_step(step, 0.01)
        p.close()
        assert len(p.captures) == 1
        assert (p.captures[0]["start_step"],
                p.captures[0]["stop_step"]) == (5, 6)

    def test_manual_window_entirely_past_is_spent_not_retried(self, tmp_path):
        be = FakeBackend()
        p = AnomalyProfiler(str(tmp_path), profile_steps=(3, 6), backend=be)
        p.before_step(10)  # resumed beyond the whole window: warns once
        assert p._manual_done
        p.after_step(10, 0.01)
        p.close()
        assert p.captures == [] and be.calls == []

    def test_run_end_mid_window_still_stops(self, tmp_path):
        be = FakeBackend()
        p = AnomalyProfiler(str(tmp_path), profile_steps=(2, 100), backend=be)
        p.before_step(1)
        p.after_step(1, 0.01)
        p.before_step(2)
        assert p.active
        p.close()
        assert not p.active
        assert be.calls[-1] == ("stop", None)
        assert len(p.captures) == 1

    def test_broken_backend_degrades_and_stops_rearming(self, tmp_path):
        p = AnomalyProfiler(str(tmp_path), window_steps=1, spike_factor=2.0,
                            max_captures=5, backend=FakeBackend(True))
        for step, t in enumerate([0.01, 0.01, 0.01, 1.0, 0.01, 1.0], 1):
            p.before_step(step)
            p.after_step(step, t)
        assert p.captures == [] and p._broken

    def test_parse_profile_steps(self):
        assert parse_profile_steps("") is None
        assert parse_profile_steps("3:7") == (3, 7)
        for bad in ("7:3", "0:4", "x:y", "3", "3:4:5"):
            with pytest.raises(ValueError):
                parse_profile_steps(bad)


# ---------------------------------------------------------------------------
# SIGUSR1 live snapshot
# ---------------------------------------------------------------------------


class TestLiveSnapshotter:
    def test_sigusr1_dumps_without_stopping(self, tmp_path):
        snap = LiveSnapshotter(
            str(tmp_path), lambda: {"step": 7, "span_tail": [{"name": "x"}]})
        with snap:
            os.kill(os.getpid(), signal.SIGUSR1)
            # the handler runs between bytecodes; this loop keeps running
            deadline = time.monotonic() + 5
            while snap.snapshots_written == 0 and time.monotonic() < deadline:
                time.sleep(0.01)
        assert snap.snapshots_written == 1
        payload = json.load(open(tmp_path / "live_snapshot_1.json"))
        assert payload["step"] == 7
        assert payload["span_tail"] == [{"name": "x"}]
        assert "MainThread" in payload["thread_stacks"]

    def test_broken_snapshot_fn_never_kills_the_run(self, tmp_path):
        def broken():
            raise RuntimeError("boom")

        snap = LiveSnapshotter(str(tmp_path), broken)
        with snap:
            os.kill(os.getpid(), signal.SIGUSR1)
            deadline = time.monotonic() + 5
            while snap.snapshots_written == 0 and time.monotonic() < deadline:
                time.sleep(0.01)
        payload = json.load(open(tmp_path / "live_snapshot_1.json"))
        assert "boom" in payload["snapshot_error"]

    def test_uninstall_restores_previous_handler(self, tmp_path):
        prev = signal.getsignal(signal.SIGUSR1)
        snap = LiveSnapshotter(str(tmp_path), dict)
        snap.install()
        snap.uninstall()
        assert signal.getsignal(signal.SIGUSR1) == prev


# ---------------------------------------------------------------------------
# Straggler detector (unit)
# ---------------------------------------------------------------------------


class TestStragglerDetector:
    def test_summary_names_argmax_host(self):
        d = StragglerDetector(factor=2.0, patience=3)
        s = d.observe(1, [{"step_time": 0.1, "data_fetch_time": 0.01},
                          {"step_time": 0.3, "data_fetch_time": 0.2},
                          {"step_time": 0.1, "data_fetch_time": 0.01}])
        assert s["step_time_argmax_host"] == 1
        assert s["step_time_max"] == pytest.approx(0.3)
        assert s["step_time_p50"] == pytest.approx(0.1)
        assert s["data_fetch_argmax_host"] == 1

    def test_persistence_needs_patience(self):
        d = StragglerDetector(factor=2.0, patience=3)
        obs = [{"step_time": 0.1}, {"step_time": 0.1}, {"step_time": 0.5}]
        d.observe(1, obs)
        d.observe(2, obs)
        assert d.counters() == {"straggler_flags": 0.0,
                                "straggler_host": -1.0}
        d.observe(3, obs)
        assert d.counters() == {"straggler_flags": 1.0,
                                "straggler_host": 2.0}

    def test_recovered_host_resets_streak_and_gauge(self):
        d = StragglerDetector(factor=2.0, patience=1)
        d.observe(1, [{"step_time": 0.1}, {"step_time": 0.1},
                      {"step_time": 0.5}])
        assert d.straggler_host == 2
        d.observe(2, [{"step_time": 0.1}, {"step_time": 0.1},
                      {"step_time": 0.11}])
        assert d.straggler_host == -1
        assert d.straggler_flags == 1  # cumulative count stands

    def test_two_host_fleet_flags_against_peer_median(self):
        # leave-one-out: each host is judged against the median of the
        # OTHER hosts. A fleet median including the straggler's own
        # time would make the 2-host threshold s > s + f — unreachable
        # for any positive peer time.
        d = StragglerDetector(factor=2.0, patience=2)
        obs = [{"step_time": 0.1}, {"step_time": 0.5}]
        d.observe(1, obs)
        assert d.straggler_host == -1  # patience not yet met
        d.observe(2, obs)
        assert d.straggler_host == 1
        assert d.straggler_flags >= 1

    def test_fewer_than_two_hosts_is_no_fleet(self):
        d = StragglerDetector()
        assert d.observe(1, [{"step_time": 0.1}]) is None
        assert d.observe(1, [None, {"step_time": 0.1}, None]) is None

    def test_validation(self):
        with pytest.raises(ValueError, match="factor"):
            StragglerDetector(factor=1.0)
        with pytest.raises(ValueError, match="patience"):
            StragglerDetector(patience=0)


# ---------------------------------------------------------------------------
# 4-host FakeBus: one delayed host surfaces in the straggler report
# ---------------------------------------------------------------------------


@pytest.mark.multihost
def test_fakebus_delayed_host_surfaces_in_straggler_report():
    from scaletorch_tpu.resilience import ResilienceManager
    from scaletorch_tpu.resilience_distributed import CoordinatedResilience
    from tests.test_resilience_distributed import run_hosts

    n, slow_host = 4, 2
    detectors = {}

    def host_fn(i, bus):
        cfg = e2e_cfg(None, sentinel_frequency=1)
        coord = CoordinatedResilience(
            ResilienceManager.from_config(cfg), bus=bus)
        if bus.is_main:
            coord.straggler = StragglerDetector(
                factor=2.0, patience=2, log_frequency=1)
            detectors[i] = coord.straggler
        for step in range(1, 6):
            t0 = time.perf_counter()
            time.sleep(0.08 if i == slow_host else 0.005)  # the "step"
            dt = time.perf_counter() - t0
            _, action = coord.after_step(
                step, {"loss": 1.0},
                telemetry={"step_time": dt, "data_fetch_time": 0.0})
            assert action == "ok"
        return coord.straggler_counters()

    results, errors = run_hosts(n, host_fn)
    assert errors == [None] * n
    det = detectors[0]
    # host 0's report names the delayed host — the fleet-debugging
    # primitive the multihost launcher lacked
    assert det.last_summary["step_time_argmax_host"] == slow_host
    assert results[0]["straggler_host"] == slow_host
    assert results[0]["straggler_flags"] >= 1
    # non-main hosts hold no detector: their counters are empty
    assert results[1] == {}


# ---------------------------------------------------------------------------
# Telemetry facade + config
# ---------------------------------------------------------------------------


class TestFacadeAndConfig:
    def test_disabled_without_dir(self):
        t = Telemetry.from_config(e2e_cfg(None))
        assert not t.enabled
        assert t.tracer is None and t.exporter is None
        assert t.profiler is None and t.snapshotter is None
        assert t.span_tail() == []
        t.export("x", {})  # no-ops
        t.flush()
        t.close()

    def test_enabled_from_config(self, tmp_path):
        cfg = e2e_cfg(None, telemetry_dir=str(tmp_path),
                      profile_on_slow_step=2.0)
        t = Telemetry.from_config(cfg, process_index=1)
        assert t.enabled and t.profiler is not None
        assert t.tracer.path.endswith("trace_proc1.trace.json")
        assert t.exporter.path.endswith("events_proc1.jsonl")
        t.close()

    def test_env_dir_present_wins_including_empty(self, tmp_path,
                                                  monkeypatch):
        cfg = e2e_cfg(None, telemetry_dir=str(tmp_path))
        monkeypatch.setenv("SCALETORCH_TPU_TELEMETRY_DIR", "")
        assert not Telemetry.from_config(cfg).enabled  # explicit off
        monkeypatch.setenv("SCALETORCH_TPU_TELEMETRY_DIR",
                           str(tmp_path / "env"))
        t = Telemetry.from_config(e2e_cfg(None))
        assert t.directory == str(tmp_path / "env")
        t.close()

    def test_config_validation(self, tmp_path):
        for kw in (dict(profile_on_slow_step=0.5),
                   dict(profile_window_steps=0),
                   dict(profile_steps="9:1"),
                   dict(straggler_factor=1.0),
                   dict(straggler_patience=0),
                   dict(log_format="yaml"),
                   dict(ft_slow_step_seconds=0.0),
                   # a profiler with nowhere to write is a config error,
                   # not a silent no-op
                   dict(profile_on_slow_step=2.0),
                   dict(profile_steps="3:5")):
            with pytest.raises(ValueError):
                e2e_cfg(None, **kw)
        # ... and valid with a directory to land in
        e2e_cfg(None, profile_on_slow_step=2.0,
                telemetry_dir=str(tmp_path))


# ---------------------------------------------------------------------------
# --log_format json
# ---------------------------------------------------------------------------


class TestJsonLogFormat:
    def test_json_formatter_wraps_and_passes_through(self):
        import logging

        from scaletorch_tpu.utils.logger import JsonFormatter

        fmt = JsonFormatter(process_index=0)
        rec = logging.LogRecord("n", logging.INFO, "f", 1,
                                "plain message", None, None)
        out = json.loads(fmt.format(rec))
        assert out["msg"] == "plain message"
        assert out["level"] == "INFO" and out["proc"] == 0
        # a metrics step record passes through AS-IS
        rec.structured_record = {"step": 3, "loss": 1.5}
        out = json.loads(fmt.format(rec))
        assert out["step"] == 3 and out["loss"] == 1.5
        assert "msg" not in out

    def test_metrics_line_carries_structured_record(self):
        from scaletorch_tpu.trainer.metrics import MetricsLogger

        ml = MetricsLogger(num_params=10, num_layers=1, num_heads=1,
                           head_dim=8, seq_len=8, tokens_per_step=8,
                           collect_system=False)
        captured = []

        class Cap(logging.Handler):
            def emit(self, r):
                captured.append(r)

        logger = logging.getLogger("scaletorch_tpu")
        handler = Cap(level=logging.INFO)
        logger.addHandler(handler)
        try:
            record = ml.log_step(1, loss=2.0, lr=1e-3, grad_norm=0.5)
        finally:
            logger.removeHandler(handler)
        assert record["loss"] == 2.0
        [logged] = [r for r in captured
                    if getattr(r, "structured_record", None)]
        # the JSON formatter's pass-through payload IS the step record
        assert logged.structured_record["loss"] == 2.0

    def test_get_logger_swaps_to_json_format_process_wide(self, capsys):
        import logging

        from scaletorch_tpu.utils.logger import JsonFormatter, get_logger

        name = "scaletorch_tpu_jsonfmt_test"
        sibling = "scaletorch_tpu_jsonfmt_test.engine"
        logger = get_logger(name)          # text first
        other = get_logger(sibling)        # a module logger, import-time
        try:
            logger = get_logger(name, log_format="json")
            assert all(isinstance(h.formatter, JsonFormatter)
                       for h in logger.handlers)
            # process-wide: the module logger created BEFORE the format
            # switch is reformatted too (fleet aggregation parses the
            # whole stream, not one logger's slice)
            assert all(isinstance(h.formatter, JsonFormatter)
                       for h in other.handlers)
            logger.info("hello")
            line = capsys.readouterr().out.strip().splitlines()[-1]
            assert json.loads(line)["msg"] == "hello"
            # format sticks for later format-less calls, and new loggers
            # adopt it
            assert (get_logger(name)._scaletorch_log_format == "json")
            fresh = get_logger("scaletorch_tpu_jsonfmt_test.late")
            assert all(isinstance(h.formatter, JsonFormatter)
                       for h in fresh.handlers)
        finally:
            get_logger(name, log_format="text")  # restore the global
            for n in (name, sibling, "scaletorch_tpu_jsonfmt_test.late"):
                logging.getLogger(n).handlers.clear()


# ---------------------------------------------------------------------------
# End-to-end: the REAL train loop with telemetry on
# ---------------------------------------------------------------------------


class TelemetryToyTrainer(ToyTrainer):
    """ToyTrainer whose step() mirrors Trainer.step's beat sites
    (data_fetch / step_dispatch + fetch timing), so the span timeline
    under test matches the production loop's."""

    def step(self, batch=None):
        self._last_data_fetch_s = 0.0
        if batch is None:
            if self._train_iter is None:
                self._train_iter = iter(self.loader)
            self._beat("data_fetch")
            t0 = time.perf_counter()
            batch = next(self._train_iter)
            self._last_data_fetch_s = time.perf_counter() - t0
        self._beat("step_dispatch")
        self.params, self.opt_state, m = self.step_fn(
            self.params, self.opt_state, batch
        )
        self.global_step += 1
        self.tokens_seen += int(np.prod(np.shape(batch["input_ids"])))
        return m


def telemetry_cfg(tmp_path, **kw):
    defaults = dict(
        telemetry_dir=str(tmp_path / "telemetry"),
        log_frequency=1,
        sentinel_frequency=1,
    )
    defaults.update(kw)
    return e2e_cfg(tmp_path, **defaults)


class TestEndToEndTelemetry:
    def test_trace_and_jsonl_from_real_train_loop(self, tmp_path):
        cfg = telemetry_cfg(tmp_path)
        t = TelemetryToyTrainer(cfg, e2e_tokens())
        t.train()
        t.close()
        assert t.global_step == 6

        # Chrome trace: valid JSON, trace-event schema, the span
        # vocabulary of the production loop
        trace_path = os.path.join(
            cfg.telemetry_dir, "trace_proc0.trace.json")
        events = json.load(open(trace_path))
        spans = [e for e in events if e.get("ph") == "X"]
        assert spans, "no spans recorded"
        for e in spans:
            assert {"name", "ts", "dur", "pid", "tid"} <= set(e)
        names = {e["name"] for e in spans}
        assert {"step_boundary", "data_fetch", "step_dispatch",
                "checkpoint_save"} <= names

        # JSONL: schema-valid, ONE train_step record per logged step
        lines = read_jsonl(os.path.join(
            cfg.telemetry_dir, "events_proc0.jsonl"))
        steps = [line for line in lines if line["kind"] == "train_step"]
        assert [s["step"] for s in steps] == [1, 2, 3, 4, 5, 6]
        for s in steps:
            assert s["v"] == SCHEMA_VERSION
            assert np.isfinite(s["loss"])

    def test_injected_slow_step_arms_one_real_profiler_window(
            self, tmp_path):
        """The acceptance drill: --ft_slow_step_at_step spikes one
        step's wall time; the detector arms EXACTLY ONE bounded
        jax.profiler window, written under --telemetry_dir."""
        cfg = telemetry_cfg(
            tmp_path,
            total_train_steps=8,
            ft_slow_step_at_step=3, ft_slow_step_seconds=0.4,
            profile_on_slow_step=3.0, profile_window_steps=2,
        )
        t = TelemetryToyTrainer(cfg, e2e_tokens())
        t.train()
        profiler = t.telemetry.profiler
        t.close()
        assert t.global_step == 8
        assert len(profiler.captures) == 1  # exactly one window
        cap = profiler.captures[0]
        assert cap["trigger"] == "slow_step"
        assert cap["stop_step"] - cap["start_step"] == 2  # bounded
        # the real jax.profiler wrote its capture under telemetry_dir
        assert cap["dir"].startswith(cfg.telemetry_dir)
        captured_files = [
            os.path.join(root, f)
            for root, _, files in os.walk(cap["dir"]) for f in files
        ]
        assert captured_files, "profiler window produced no artifacts"

    def test_crash_report_embeds_span_timeline_tail(self, tmp_path):
        from scaletorch_tpu.resilience import TrainingDivergedError

        cfg = telemetry_cfg(tmp_path, ft_nan_at_step=3,
                            divergence_policy="abort")
        t = TelemetryToyTrainer(cfg, e2e_tokens())
        with pytest.raises(TrainingDivergedError):
            t.train()
        t.close()
        [report_path] = [
            os.path.join(str(tmp_path / "crash_reports"), f)
            for f in os.listdir(tmp_path / "crash_reports")
        ]
        report = json.load(open(report_path))
        tail = report["span_timeline_tail"]
        assert tail, "crash report carries no span timeline"
        assert {e["name"] for e in tail} >= {"data_fetch", "step_dispatch"}

    def test_engine_metrics_ride_the_same_export_path(self, tmp_path):
        """Serving parity: EngineMetrics snapshots land on the SAME
        schema-versioned JSONL stream, and the engine tick records its
        span vocabulary."""
        import jax
        import jax.numpy as jnp

        from scaletorch_tpu.inference import InferenceEngine, SamplingParams
        from scaletorch_tpu.models import llama

        cfg = llama.LlamaConfig(
            vocab_size=64, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, dtype=jnp.float32,
        )
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        tracer = SpanTracer(str(tmp_path / "serve.trace.json"), role="serve")
        exporter = TelemetryExporter(str(tmp_path / "serve.jsonl"))
        eng = InferenceEngine(
            params, cfg, max_slots=2, max_seq=16, prefill_len=8,
            sampling=SamplingParams(temperature=0.0),
            tracer=tracer, exporter=exporter, monitor_every=4,
        )
        eng.submit([1, 2, 3], max_new_tokens=5)
        results = eng.run()
        # idle polling must not grow the durable stream: decode_steps is
        # parked, so cadence-multiple ticks export nothing new
        written = exporter.events_written
        for _ in range(5):
            eng.step()
        assert exporter.events_written == written
        # a drain() straight after run() (the common shutdown sequence)
        # makes no progress either — the terminal emit is deduped, not
        # appended as an identical duplicate record
        eng.drain()
        assert exporter.events_written == written
        tracer.close()
        exporter.close()
        assert all(r.outcome == "ok" for r in results.values())
        names = {e["name"] for e in json.load(
            open(tmp_path / "serve.trace.json")) if e.get("ph") == "X"}
        assert {"tick", "admission", "prefill", "decode"} <= names
        lines = read_jsonl(str(tmp_path / "serve.jsonl"))
        assert lines and all(
            line["kind"] == "engine_metrics" and line["v"] == SCHEMA_VERSION
            for line in lines)
        # the drain-exit snapshot carries the terminal counters
        assert lines[-1]["requests_ok"] == 1

    def test_disabled_overhead_within_noise(self, tmp_path):
        """Telemetry off: the instrumented loop's per-step telemetry
        work is sub-microsecond-scale (vs millisecond-scale steps), and
        the full train() loop stays within a loose factor of driving
        the bare step function directly."""
        # (a) the per-step hook cost when disabled: branches only
        tel = Telemetry.disabled()
        coordinator_counters = {}

        def per_step_hooks():
            if tel.tracer is not None:
                tel.tracer.phase("step_boundary")
            if tel.profiler is not None:
                tel.profiler.after_step(0, 0.0)
            return {"step_time": 0.0, **coordinator_counters}

        import timeit

        per_call = timeit.timeit(per_step_hooks, number=20_000) / 20_000
        assert per_call < 5e-6  # noise against a >= ms CPU toy step

        # (b) relate the hook cost to the real step: the disabled-path
        # telemetry work must be < 5% of one measured toy step. (A full
        # loop-vs-loop wall-clock comparison would be dominated by the
        # loader / coordinator / metrics costs the loop pays with or
        # without this PR — the marginal telemetry cost is the hooks.)
        cfg = e2e_cfg(None, total_train_steps=40, log_frequency=10_000,
                      sentinel_frequency=0, handle_preemption=False)
        t = TelemetryToyTrainer(cfg, e2e_tokens(128))
        assert not t.telemetry.enabled
        t.train(num_steps=8)  # warm the jit cache; the loop runs clean
        batch = next(iter(t.loader))
        for _ in range(4):  # warm
            t.step_fn(t.params, t.opt_state, batch)
        t0 = time.perf_counter()
        for _ in range(16):
            t.params, t.opt_state, _ = t.step_fn(
                t.params, t.opt_state, batch)
        bare = (time.perf_counter() - t0) / 16
        t.close()
        assert per_call < 0.05 * bare, (
            f"disabled telemetry hooks cost {per_call * 1e6:.2f}us/step "
            f"vs a {bare * 1e3:.3f}ms bare step (>= 5%)"
        )
