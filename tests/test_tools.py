"""Tools tier: verify_weights self-test + profile breakdown math.

(The bench tools are thin CLIs over scaletorch_tpu.benchmark, covered by
tests/test_benchmark.py; pp_schedule_compare's prediction model is
asserted against its own measured output in its docstring run.)
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def test_verify_weights_synthetic_self_test(capsys):
    from tools.verify_weights import synthetic_self_test

    assert synthetic_self_test()
    out = capsys.readouterr().out
    assert "forward: PASS" in out
    assert "backward: PASS" in out
    assert "RESULT: OK" in out


def test_profile_flops_breakdown_matches_mfu_formula():
    from scaletorch_tpu.models.presets import preset
    from tools.profile_mfu import flops_breakdown

    p = preset("qwen3-0.6b")
    seq = 8192
    br = flops_breakdown(p, seq)
    assert br["forward"] == br["linear"] + br["attention"] + br["embed_head"]
    # attention term matches the shared MFU formula's 12*L*heads*hd*seq
    # (utils/misc.get_mfu): 3x the forward 4*L*heads*hd*seq
    assert 3 * br["attention"] == 12 * p["num_hidden_layers"] * \
        p["num_attention_heads"] * p["head_dim"] * seq
