"""Tools tier: verify_weights self-test + profile breakdown math.

(The bench tools are thin CLIs over scaletorch_tpu.benchmark, covered by
tests/test_benchmark.py; pp_schedule_compare's prediction model is
asserted against its own measured output in its docstring run.)
"""

from __future__ import annotations

import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


@pytest.mark.slow
def test_verify_weights_synthetic_self_test(capsys):
    from tools.verify_weights import synthetic_self_test

    assert synthetic_self_test()
    out = capsys.readouterr().out
    assert "forward: PASS" in out
    assert "backward: PASS" in out
    assert "RESULT: OK" in out


def test_profile_flops_breakdown_matches_mfu_formula():
    from scaletorch_tpu.models.presets import preset
    from tools.profile_mfu import flops_breakdown

    p = preset("qwen3-0.6b")
    seq = 8192
    br = flops_breakdown(p, seq)
    assert br["forward"] == br["linear"] + br["attention"] + br["embed_head"]
    # attention term matches the shared MFU formula's 12*L*heads*hd*seq
    # (utils/misc.get_mfu): 3x the forward 4*L*heads*hd*seq
    assert 3 * br["attention"] == 12 * p["num_hidden_layers"] * \
        p["num_attention_heads"] * p["head_dim"] * seq


def test_group_hosts_slice_major_ranks():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "group_hosts", os.path.join(REPO, "scripts", "group_hosts.py"))
    gh = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(gh)

    lines = [
        "t1v-n-abc-w-0",          # slice t1v-n-abc
        "10.0.0.1 rack-b",        # explicit rack column
        "t1v-n-abc-w-1",
        "10.0.0.2 rack-b",
        "bare-host",              # its own group
    ]
    groups = gh.group_hosts(lines)
    assert groups["t1v-n-abc"] == ["t1v-n-abc-w-0", "t1v-n-abc-w-1"]
    assert groups["rack-b"] == ["10.0.0.1", "10.0.0.2"]
    assert groups["bare-host"] == ["bare-host"]
    # slice-major contiguous ranks: same slice -> adjacent process indices
    ranks = gh.rank_assignment(groups)
    by_key = {}
    for rank, _, key in ranks:
        by_key.setdefault(key, []).append(rank)
    for key, rs in by_key.items():
        assert rs == list(range(rs[0], rs[0] + len(rs))), (key, rs)
    # rendered output round-trips through the grouped-file parser
    assert gh.group_hosts(gh.render(groups).splitlines()) == groups


def test_optimize_mfu_gen_detection():
    """The AOT prefilter's HBM budget must track the actual chip: the
    device-kind -> generation mapping is a pure function, tested here."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "omfu", os.path.join(REPO, "tools", "optimize_mfu.py"))
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    # explicit flag always wins
    assert m._detect_gen("v5p") == "v5p"
    assert m._detect_gen("v6e") == "v6e"
    # detection falls back to the v5e budget with no device/unknown kind
    assert m._detect_gen(None) in ("v5e", "v6e", "v5p", "v4")


@pytest.mark.slow
def test_bench_moe_dispatch_mechanics(tmp_path):
    """Both dispatch modes run the same MoE geometry and produce the SAME
    loss (identical routing math); the speedup field is emitted. CPU-mesh
    numbers attest mechanics only (documented in the tool)."""
    import json
    import subprocess
    import sys as _sys

    out = tmp_path / "moe.json"
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    r = subprocess.run(
        [_sys.executable, os.path.join(REPO, "tools", "bench_moe_dispatch.py"),
         "--cpu", "--model", "moe-tiny", "--ep", "2", "--dp", "2",
         "--seq", "256", "--steps", "2", "--warmup", "1",
         "--out", str(out)],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    data = json.loads(out.read_text())
    for m in ("einsum", "index"):
        assert "error" not in data[m], data[m]
    assert data["index"]["loss"] == pytest.approx(
        data["einsum"]["loss"], rel=2e-4)
    assert "index_speedup_vs_einsum" in data


@pytest.mark.slow
def test_bench_cp_compare_mechanics(tmp_path):
    """All three CP strategies run at one geometry and produce the same
    loss (exact attention each way); speedups are emitted. CPU-mesh
    numbers attest mechanics only (documented in the tool)."""
    import json
    import subprocess
    import sys as _sys

    out = tmp_path / "cp.json"
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    r = subprocess.run(
        [_sys.executable, os.path.join(REPO, "tools", "bench_cp_compare.py"),
         "--cpu", "--model", "dense-tiny", "--cp", "2", "--dp", "2",
         "--seq", "256", "--steps", "2", "--warmup", "1",
         "--out", str(out)],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    data = json.loads(out.read_text())
    for s in ("ring_contiguous", "ring_zigzag", "ulysses"):
        assert "error" not in data[s], data[s]
    # exact attention under every strategy, to fp32 reduction-order noise
    base = data["ring_contiguous"]["loss"]
    assert data["ring_zigzag"]["loss"] == pytest.approx(base, rel=2e-4)
    assert data["ulysses"]["loss"] == pytest.approx(base, rel=2e-4)
    assert "ring_zigzag_speedup_vs_contiguous" in data
