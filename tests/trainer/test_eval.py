"""Validation path: make_spmd_eval_step + Trainer.evaluate.

The eval step must compute the SAME objective as the train step (whose
reported loss is pre-update) — checked on identical params/batch — and
the Trainer must produce a finite validation loss from its disjoint
synthetic stream.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scaletorch_tpu.config import ScaleTorchTPUArguments
from scaletorch_tpu.models.llama import LlamaConfig, forward, init_params
from scaletorch_tpu.parallel.mesh import MeshManager
from scaletorch_tpu.parallel.spmd import (
    make_spmd_eval_step,
    make_spmd_train_step,
    shard_params,
)
from scaletorch_tpu.trainer.optimizer import create_optimizer

CFG = LlamaConfig(
    vocab_size=128, hidden_size=32, intermediate_size=64,
    num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
    head_dim=16, dtype=jnp.float32,
)


def _batch(accum=2, rows=4, seq=16, seed=0):
    rng = np.random.default_rng(seed)
    toks = rng.integers(0, CFG.vocab_size, (accum, rows, seq + 1))
    return {
        "input_ids": toks[:, :, :-1].astype(np.int32),
        "target_ids": toks[:, :, 1:].astype(np.int32),
        "position_ids": np.broadcast_to(
            np.arange(seq, dtype=np.int32), (accum, seq)
        ).copy(),
    }


@pytest.mark.parametrize("dims", [dict(dp=4, tp=2), dict(pp=2, dp=2, tp=2)])
@pytest.mark.slow
def test_eval_step_matches_train_loss(dims):
    mm = MeshManager(**dims)
    params = init_params(jax.random.PRNGKey(0), CFG)
    tcfg = ScaleTorchTPUArguments(
        learning_rate=1e-3, total_train_steps=10, warmup_steps=0
    )
    tx, _ = create_optimizer(tcfg, include_clip=False)
    step_fn, p_specs, o_specs = make_spmd_train_step(
        mm, forward, CFG, tx, params, donate=False, pp_schedule="afab",
    )
    eval_fn, ep_specs = make_spmd_eval_step(mm, forward, CFG)
    assert ep_specs == p_specs

    params_s = shard_params(mm, params, p_specs)
    batch = _batch()
    val = float(eval_fn(params_s, batch))
    _, _, metrics = step_fn(
        params_s, shard_params(mm, tx.init(params), o_specs), batch
    )
    assert val == pytest.approx(float(metrics["loss"]), rel=1e-5)


@pytest.mark.slow
def test_trainer_evaluate_synthetic():
    cfg = ScaleTorchTPUArguments(
        model_type="llama", hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, vocab_size=128, sequence_length=16,
        max_position_embeddings=64,
        data_parallel_size=8, synthetic_data=True, total_train_steps=2,
        dtype="float32", eval_frequency=1, eval_steps=2,
        donate_params=False, log_frequency=100,
    )
    from scaletorch_tpu.trainer.trainer import Trainer

    tr = Trainer(cfg)
    val = tr.evaluate()
    assert val is not None and np.isfinite(val)
    # ~ln(128) at init
    assert val == pytest.approx(np.log(128), rel=0.2)
    # the train loop logs val_loss without erroring
    tr.train(num_steps=1)


@pytest.mark.slow
def test_trainer_evaluate_with_interleaved_pp():
    """The eval step must run the SAME engine as training when
    pp_engine='interleaved' — an afab eval graph over interleaved-order
    params would stack the wrong layers per stage. Two trainers on
    identical data, one per engine: val losses must agree."""
    def mk(engine, vpp):
        return ScaleTorchTPUArguments(
            model_type="llama", hidden_size=32, intermediate_size=64,
            num_hidden_layers=4, num_attention_heads=4,
            num_key_value_heads=2, head_dim=16, vocab_size=128,
            sequence_length=16, max_position_embeddings=64,
            pipeline_parallel_size=2, data_parallel_size=4,
            pp_engine=engine, pp_virtual_stages=vpp,
            synthetic_data=True, total_train_steps=2, dtype="float32",
            eval_frequency=1, eval_steps=2,
            donate_params=False, log_frequency=100,
        )

    from scaletorch_tpu.trainer.trainer import Trainer

    vals = {}
    for engine, vpp in (("afab", 1), ("interleaved", 2)):
        tr = Trainer(mk(engine, vpp))
        try:
            vals[engine] = tr.evaluate()
        finally:
            tr.close()
    assert np.isfinite(vals["interleaved"])
    assert vals["interleaved"] == pytest.approx(vals["afab"], rel=1e-5)


@pytest.mark.slow
def test_trainer_bf16_master_weights():
    """param_dtype=bfloat16 (torch-parity memory mode, bench 1.7B/4B rows):
    params AND adam moments stay bf16 across jitted steps — a dtype drift
    would change the jit signature / break donation — and loss decreases."""
    import jax.numpy as jnp

    cfg = ScaleTorchTPUArguments(
        model_type="llama", hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, vocab_size=128, sequence_length=16,
        max_position_embeddings=64, learning_rate=3e-3,
        data_parallel_size=4, tensor_parallel_size=2,
        synthetic_data=True, total_train_steps=12,
        dtype="bfloat16", param_dtype="bfloat16",
        donate_params=False, log_frequency=100,
        eval_frequency=1000, eval_steps=2,
    )
    from scaletorch_tpu.trainer.trainer import Trainer

    tr = Trainer(cfg)
    assert all(p.dtype == jnp.bfloat16 for p in jax.tree.leaves(tr.params))
    p0 = jax.tree.map(lambda x: np.asarray(x, np.float32), tr.params)
    tr.train(num_steps=12)
    val = tr.evaluate()
    # dtype stability across jitted steps (a drift would respecialise the
    # jit signature / break donation)
    assert all(p.dtype == jnp.bfloat16 for p in jax.tree.leaves(tr.params))
    # adam mu/nu inherit the bf16 param dtype (param-shaped leaves only —
    # the step counter and schedule state stay scalar int/fp32)
    mu_like = [
        o for o in jax.tree.leaves(tr.opt_state)
        if getattr(o, "ndim", 0) >= 1 and o.size > 4
    ]
    assert mu_like and all(o.dtype == jnp.bfloat16 for o in mu_like)
    assert val is not None and np.isfinite(val)
    moved = [
        float(np.abs(np.asarray(b, np.float32) - a).max())
        for a, b in zip(jax.tree.leaves(p0), jax.tree.leaves(tr.params))
    ]
    assert max(moved) > 0
