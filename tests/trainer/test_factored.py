"""Sharding-aware Adafactor (trainer/factored.py) numerics.

Two contracts: (1) with replicated specs it reproduces optax.adafactor
bitwise; (2) under a tp-sharded shard_map its updates match the
unsharded computation — the factored row/col stats, block-RMS clip, and
parameter-scale reductions all cross shard boundaries correctly.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from scaletorch_tpu.trainer.factored import adafactor_sharded


def _params():
    return {
        "colw": jax.random.normal(jax.random.key(0), (256, 384)),
        "roww": jax.random.normal(jax.random.key(1), (384, 256)),
        "norm": jax.random.normal(jax.random.key(2), (256,)),
        "small": jax.random.normal(jax.random.key(3), (16, 8)),
    }


class TestUnshardedParity:
    @pytest.mark.slow
    def test_matches_optax_adafactor_over_steps(self):
        params = _params()
        specs = jax.tree.map(lambda _: P(), params)
        ref = optax.adafactor(learning_rate=0.01)
        mine = adafactor_sharded(0.01, specs)

        p1 = jax.tree.map(jnp.copy, params)
        p2 = jax.tree.map(jnp.copy, params)
        s1, s2 = ref.init(p1), mine.init(p2)
        for i in range(4):
            g = jax.tree.map(lambda p: jnp.sin(p) * 0.3 + 0.01 * i, params)
            u1, s1 = ref.update(g, s1, p1)
            p1 = optax.apply_updates(p1, u1)
            u2, s2 = mine.update(g, s2, p2)
            p2 = optax.apply_updates(p2, u2)
        for k in params:
            np.testing.assert_array_equal(np.asarray(p1[k]), np.asarray(p2[k]))

    def test_factored_state_is_sublinear(self):
        params = _params()
        mine = adafactor_sharded(0.01, jax.tree.map(lambda _: P(), params))
        state = mine.init(params)
        n_params = sum(p.size for p in jax.tree.leaves(params))
        n_state = sum(s.size for s in jax.tree.leaves(state))
        # the two big matrices must be factored: state well under half the
        # param count (the small/1-D leaves keep a full second moment)
        assert n_state < 0.2 * n_params


class TestShardedParity:
    @pytest.fixture
    def mesh(self):
        return Mesh(np.array(jax.devices()[:2]), ("tp",))

    @pytest.mark.slow
    def test_tp2_updates_match_unsharded(self, mesh):
        params = _params()
        specs = {"colw": P(None, "tp"), "roww": P("tp", None),
                 "norm": P(), "small": P()}
        grads = jax.tree.map(lambda p: jnp.cos(p) * 0.5, params)

        ref = adafactor_sharded(0.01, jax.tree.map(lambda _: P(), params))
        u_ref, _ = ref.update(grads, ref.init(params), params)

        tx = adafactor_sharded(0.01, specs, axis_sizes={"tp": 2})
        state_specs = tx.state_specs(params)

        def axes_of(spec):
            out = ()
            for e in spec:
                if e is not None:
                    out += tuple(e) if isinstance(e, tuple) else (e,)
            return out

        def step(p, s, g):
            from scaletorch_tpu.parallel.tensor_parallel import pvary_missing

            is_p = lambda x: isinstance(x, P)  # noqa: E731
            g = jax.tree.map(lambda x, sp: pvary_missing(x, axes_of(sp)),
                             g, specs, is_leaf=is_p)
            p = jax.tree.map(lambda x, sp: pvary_missing(x, axes_of(sp)),
                             p, specs, is_leaf=is_p)
            return tx.update(g, s, p)

        sharded = jax.shard_map(
            step, mesh=mesh, in_specs=(specs, state_specs, specs),
            out_specs=(specs, state_specs),
        )
        u_sh, _ = sharded(params, tx.init(params), grads)
        for k in params:
            np.testing.assert_allclose(
                np.asarray(u_ref[k]), np.asarray(u_sh[k]),
                rtol=1e-6, atol=1e-8,
            )

    def test_missing_axis_sizes_raises(self, mesh):
        params = {"w": jnp.ones((256, 384))}
        specs = {"w": P("tp", None)}
        tx = adafactor_sharded(0.01, specs)  # no axis_sizes

        def step(p, s, g):
            from scaletorch_tpu.parallel.tensor_parallel import pvary_missing

            g = {"w": pvary_missing(g["w"], ("tp",))}
            p = {"w": pvary_missing(p["w"], ("tp",))}
            return tx.update(g, s, p)

        ss = tx.state_specs(params)
        sharded = jax.shard_map(step, mesh=mesh,
                                in_specs=(specs, ss, specs),
                                out_specs=(specs, ss))
        with pytest.raises(ValueError, match="axis_sizes"):
            sharded(params, tx.init(params),
                    {"w": jnp.ones((256, 384))})


class TestTrainerIntegration:
    @pytest.mark.slow
    def test_spmd_step_with_adafactor_tp2(self):
        """End-to-end: Trainer with optimizer_name=adafactor on a tp2xdp4
        mesh trains without NaN and keeps the factored state sharded."""
        from scaletorch_tpu.config import ScaleTorchTPUArguments
        from scaletorch_tpu.trainer.trainer import Trainer

        cfg = ScaleTorchTPUArguments(
            model_type="llama", hidden_size=128, intermediate_size=256,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            head_dim=32, vocab_size=256, sequence_length=16,
            max_position_embeddings=64, learning_rate=1e-2,
            data_parallel_size=4, tensor_parallel_size=2,
            synthetic_data=True, total_train_steps=3,
            optimizer_name="adafactor", donate_params=False,
            log_frequency=100,
        )
        tr = Trainer(cfg)
        p0 = jax.tree.map(lambda x: np.asarray(x, np.float32), tr.params)
        out = tr.train(num_steps=3)
        assert np.isfinite(out.get("loss", np.nan)) or out == {}
        moved = [
            float(np.abs(np.asarray(b, np.float32) - a).max())
            for a, b in zip(jax.tree.leaves(p0), jax.tree.leaves(tr.params))
        ]
        assert max(moved) > 0
