"""LR schedule math (parity: reference tests of lr_scheduler registry)."""

import pytest

from scaletorch_tpu.config import ScaleTorchTPUArguments
from scaletorch_tpu.trainer.lr_scheduler import create_lr_scheduler, register_scheduler


def args(**kw):
    return ScaleTorchTPUArguments(
        total_train_steps=100, learning_rate=1e-2, **kw
    )


class TestSchedules:
    def test_cosine_warmup_and_floor(self):
        s = create_lr_scheduler(args(lr_scheduler_type="cosine", warmup_steps=10,
                                     min_lr_ratio=0.1))
        assert float(s(0)) == pytest.approx(0.0)
        assert float(s(5)) == pytest.approx(0.5e-2, rel=1e-6)
        assert float(s(10)) == pytest.approx(1e-2, rel=1e-6)
        assert float(s(100)) == pytest.approx(1e-3, rel=1e-4)

    def test_warmup_ratio(self):
        s = create_lr_scheduler(args(lr_scheduler_type="constant", warmup_ratio=0.2))
        assert float(s(10)) == pytest.approx(0.5e-2, rel=1e-6)
        assert float(s(20)) == pytest.approx(1e-2, rel=1e-6)
        assert float(s(99)) == pytest.approx(1e-2, rel=1e-6)

    def test_linear_decay(self):
        s = create_lr_scheduler(args(lr_scheduler_type="linear", min_lr_ratio=0.0))
        assert float(s(0)) == pytest.approx(1e-2, rel=1e-6)
        assert float(s(50)) == pytest.approx(0.5e-2, rel=1e-4)
        assert float(s(100)) == pytest.approx(0.0, abs=1e-8)

    def test_step_decay(self):
        s = create_lr_scheduler(args(lr_scheduler_type="step", step_size=10,
                                     step_gamma=0.5))
        assert float(s(9)) == pytest.approx(1e-2, rel=1e-6)
        assert float(s(10)) == pytest.approx(0.5e-2, rel=1e-6)
        assert float(s(20)) == pytest.approx(0.25e-2, rel=1e-6)

    def test_onecycle_peak(self):
        s = create_lr_scheduler(args(lr_scheduler_type="onecycle"))
        peak = max(float(s(i)) for i in range(100))
        assert peak == pytest.approx(1e-2, rel=1e-3)

    def test_polynomial(self):
        s = create_lr_scheduler(args(lr_scheduler_type="polynomial",
                                     min_lr_ratio=0.0, poly_power=1.0))
        assert float(s(50)) == pytest.approx(0.5e-2, rel=1e-4)

    def test_unknown_raises(self):
        with pytest.raises(KeyError, match="unknown lr scheduler"):
            create_lr_scheduler(args(lr_scheduler_type="nope"))

    def test_register_custom(self):
        @register_scheduler("fixed42")
        def _fixed(cfg):
            return lambda step: 42.0

        s = create_lr_scheduler(args(lr_scheduler_type="fixed42"))
        assert s(7) == 42.0
