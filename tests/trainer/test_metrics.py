"""MetricsLogger: extras plumbing + JSON history dump (reference
monitor.py:220-250 save_stats role)."""

import json

from scaletorch_tpu.trainer.metrics import MetricsLogger


def make_logger(**kw):
    defaults = dict(
        num_params=1_000_000, num_layers=2, num_heads=4, head_dim=16,
        seq_len=128, tokens_per_step=256, num_chips=1, log_frequency=1,
        peak_flops=1e12,
    )
    defaults.update(kw)
    return MetricsLogger(**defaults)


class TestExtras:
    def test_extras_reach_record(self):
        m = make_logger()
        rec = m.log_step(1, loss=2.0, lr=1e-3, grad_norm=0.5,
                         extras={"moe_dropped_fraction": 0.01,
                                 "moe_load_cv": 0.3})
        assert rec["moe_dropped_fraction"] == 0.01
        assert rec["moe_load_cv"] == 0.3

    def test_non_logging_step_skips(self):
        m = make_logger(log_frequency=10)
        assert m.log_step(3, loss=2.0, lr=1e-3, grad_norm=0.5) == {}


class TestSaveJson:
    def test_round_trip(self, tmp_path):
        m = make_logger()
        for step in range(1, 4):
            m.log_step(step, loss=3.0 - step * 0.1, lr=1e-3, grad_norm=1.0)
        path = m.save_json(str(tmp_path / "perf" / "log.json"))
        with open(path) as f:
            data = json.load(f)
        assert len(data["records"]) == 3
        assert data["records"][0]["loss"] == 2.9
        assert data["num_params"] == 1_000_000
        # windows after the first logged step carry rate metrics
        assert "tokens_per_second" in data["records"][-1]
        assert data["summary"]["mean_tokens_per_second"] > 0


class TestSystemTelemetry:
    """Reference PerformanceMonitor parity (utils/monitor.py:69-162):
    host CPU/memory fields ride every logged record and the JSON dump."""

    def test_host_fields_in_records_and_json(self, tmp_path):
        m = make_logger()
        rec = m.log_step(1, loss=2.0, lr=1e-3, grad_norm=0.5)
        for k in ("host_cpu_percent", "host_mem_percent",
                  "host_mem_used_gb", "process_rss_gb", "load_avg_1m"):
            assert k in rec, k
        assert rec["process_rss_gb"] > 0
        assert 0 <= rec["host_mem_percent"] <= 100
        path = m.save_json(str(tmp_path / "log.json"))
        with open(path) as f:
            data = json.load(f)
        assert "host_cpu_percent" in data["records"][0]
        assert data["summary"]["max_process_rss_gb"] > 0

    def test_opt_out(self):
        m = make_logger(collect_system=False)
        rec = m.log_step(1, loss=2.0, lr=1e-3, grad_norm=0.5)
        assert "host_cpu_percent" not in rec

    def test_accelerator_env_source(self, tmp_path, monkeypatch):
        """Power/temp ride the record when a platform source exists
        (TPU_METRICS_DIR sidecar files) and are ABSENT otherwise — never
        fabricated. hwmon is stubbed out so only the sidecar path is
        under test (a dev box's coretemp must not leak in)."""
        import scaletorch_tpu.utils.monitor as monitor_mod
        from scaletorch_tpu.utils.monitor import read_accelerator_environment

        monkeypatch.setattr(monitor_mod.glob, "glob", lambda pattern: [])
        monkeypatch.delenv("TPU_METRICS_DIR", raising=False)
        base = read_accelerator_environment()
        # this sandbox has no hwmon; nothing may be invented
        assert "accel_power_w" not in base and "accel_temp_c" not in base

        (tmp_path / "power").write_text("142.5\n")
        (tmp_path / "temp").write_text("61.0\n")
        monkeypatch.setenv("TPU_METRICS_DIR", str(tmp_path))
        env = read_accelerator_environment()
        assert env["accel_power_w"] == 142.5
        assert env["accel_temp_c"] == 61.0
        # and they flow into a sampled record
        from scaletorch_tpu.utils.monitor import SystemMonitor

        rec = SystemMonitor().sample(1)
        assert rec["accel_power_w"] == 142.5

    def test_hwmon_attribution_by_chip_name(self, tmp_path, monkeypatch):
        """A coretemp/NVMe hwmon sensor must surface as hwmon_*, never as
        accel_* — only chips whose driver name matches an accelerator
        (tpu/accel/apex/npu) get chip attribution (ADVICE r4)."""
        import scaletorch_tpu.utils.monitor as monitor_mod
        from scaletorch_tpu.utils.monitor import read_accelerator_environment

        host = tmp_path / "hwmon0"
        host.mkdir()
        (host / "name").write_text("coretemp\n")
        (host / "temp1_input").write_text("45000\n")
        accel = tmp_path / "hwmon1"
        accel.mkdir()
        (accel / "name").write_text("apex\n")
        (accel / "temp1_input").write_text("61000\n")
        (accel / "power1_average").write_text("142500000\n")
        monkeypatch.setattr(
            monitor_mod.glob, "glob", lambda pattern: [str(host), str(accel)]
        )
        monkeypatch.delenv("TPU_METRICS_DIR", raising=False)
        env = read_accelerator_environment()
        assert env["hwmon_temp_c"] == 45.0       # host CPU, not the chip
        assert env["accel_temp_c"] == 61.0
        assert env["accel_power_w"] == 142.5
        # host-only box: accel_* entirely absent
        monkeypatch.setattr(
            monitor_mod.glob, "glob", lambda pattern: [str(host)]
        )
        env = read_accelerator_environment()
        assert "accel_temp_c" not in env and "accel_power_w" not in env

    def test_ring_buffer_caps_history(self):
        from scaletorch_tpu.utils.monitor import SystemMonitor

        mon = SystemMonitor(max_records=4)
        for i in range(10):
            mon.sample(i)
        assert len(mon.records) == 4
        assert mon.records[-1]["step"] == 9
        s = mon.summary()
        assert "mean_host_cpu_percent" in s and "max_load_avg_1m" in s
