"""Trainer.step() — the public per-step API (ADVICE r4 #4).

Contracts under test (code-review r5): tokens_seen counts the batch
actually trained on (not the loader's nominal shape), and
load_checkpoint drops the persistent step() iterator so the
set_state fast-forward actually takes effect on the next draw.
"""

import numpy as np
import pytest

from scaletorch_tpu.config import ScaleTorchTPUArguments


def _cfg(**kw):
    defaults = dict(
        model_type="llama", hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        vocab_size=64, sequence_length=16, max_position_embeddings=32,
        data_parallel_size=8, micro_batch_size=1,
        gradient_accumulation_steps=2, synthetic_data=True,
        total_train_steps=8, dtype="float32", donate_params=False,
        log_frequency=100,
    )
    defaults.update(kw)
    return ScaleTorchTPUArguments(**defaults)


@pytest.mark.slow
def test_step_counts_actual_batch_tokens():
    from scaletorch_tpu.trainer.trainer import Trainer

    t = Trainer(_cfg())
    try:
        m = t.step()  # draws from the loader
        assert np.isfinite(float(m["loss"]))
        assert t.global_step == 1
        assert t.tokens_seen == t.loader.tokens_per_step
        # caller-supplied batch with HALF the microbatches: accounting
        # must follow the batch, not the loader's nominal shape
        batch = next(iter(t.loader))
        half = {k: v[:1] for k, v in batch.items()}
        t.step(batch=half)
        assert t.tokens_seen == (
            t.loader.tokens_per_step + half["input_ids"].size
        )
    finally:
        t.close()


@pytest.mark.slow
def test_auto_virtual_stages_resolves_and_trains():
    """pp_virtual_stages=0: the Trainer picks the largest divisor <= 4 of
    the per-rank layer count (4 layers / pp2 -> vpp 2) and the resolved
    value flows into the engine, checkpoint metadata, and a working step."""
    from scaletorch_tpu.trainer.trainer import Trainer

    t = Trainer(_cfg(num_hidden_layers=4, pipeline_parallel_size=2,
                     data_parallel_size=4, pp_engine="interleaved",
                     pp_virtual_stages=0))
    try:
        assert t._pp_vpp == 2
        # the caller's cfg keeps the sentinel: reusing it for another
        # model must re-resolve, not inherit this model's vpp
        assert t.cfg.pp_virtual_stages == 0
        assert t._layer_storage() == "interleaved_pp2_vpp2"
        m = t.step()
        assert np.isfinite(float(m["loss"]))
    finally:
        t.close()


@pytest.mark.slow
def test_resume_across_pp_engines_refuses_scrambled_layers(tmp_path):
    """The interleave permutation preserves shapes, so resuming an afab
    checkpoint under pp_engine='interleaved' (or vice versa) can only be
    caught by the layer_storage metadata — it must raise, not silently
    train a scrambled layer stack (code-review r5)."""
    from scaletorch_tpu.trainer.trainer import Trainer

    def cfg(**kw):
        return _cfg(num_hidden_layers=4, pipeline_parallel_size=2,
                    data_parallel_size=4, checkpoint_dir=str(tmp_path), **kw)

    t = Trainer(cfg())
    try:
        t.step()
        t.save_checkpoint()
        t._ckpt_mgr.wait()
    finally:
        t.close()

    t2 = Trainer(cfg(pp_engine="interleaved", pp_virtual_stages=2,
                     resume_from_checkpoint=True))
    try:
        with pytest.raises(ValueError, match="layer_storage|order"):
            t2.load_checkpoint()
    finally:
        t2.close()


@pytest.mark.slow
@pytest.mark.parametrize("optimizer", ["adamw", "adafactor"])
def test_convert_layer_storage_roundtrips_resume(tmp_path, optimizer):
    """tools/convert_layer_storage.py is the documented path across the
    engine boundary: train afab 2 steps + save, convert the checkpoint
    to interleaved order, resume under pp_engine='interleaved' for 2
    more steps — final params (deinterleaved) must match an
    uninterrupted 4-step afab run on the same stream. adafactor covers
    the optimizer-state corner: (1,) placeholders and layer-reduced
    factored stats under the mirrored 'layers' subtree must pass through
    the permutation untouched (code-review r5)."""
    import subprocess
    import sys

    import jax

    from scaletorch_tpu.parallel.pipeline_parallel import (
        deinterleave_stacked_params,
    )
    from scaletorch_tpu.trainer.trainer import Trainer

    def cfg(**kw):
        return _cfg(num_hidden_layers=4, pipeline_parallel_size=2,
                    data_parallel_size=4, micro_batch_size=4,
                    total_train_steps=4, optimizer_name=optimizer, **kw)

    # ground truth: uninterrupted afab
    t_ref = Trainer(cfg())
    try:
        for _ in range(4):
            t_ref.step()
        ref = jax.device_get(t_ref.params)
    finally:
        t_ref.close()

    src = tmp_path / "afab"
    t1 = Trainer(cfg(checkpoint_dir=str(src)))
    try:
        t1.step()
        t1.step()
        t1.save_checkpoint()
        t1._ckpt_mgr.wait()
    finally:
        t1.close()

    dst = tmp_path / "vpp2"
    import os

    tool = os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))),
        "tools", "convert_layer_storage.py")
    proc = subprocess.run(
        [sys.executable, tool, "--ckpt", str(src), "--out", str(dst),
         "--to", "interleaved", "--pp", "2", "--vpp", "2"],
        capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "model_order -> interleaved_pp2_vpp2" in proc.stdout

    t2 = Trainer(cfg(pp_engine="interleaved", pp_virtual_stages=2,
                     checkpoint_dir=str(dst), resume_from_checkpoint=True))
    try:
        t2.load_checkpoint()
        assert t2.global_step == 2
        # synthetic stream has no set_state: skip the 2 consumed batches
        # and feed explicitly (same pattern as the uneven-PP resume test)
        it = iter(t2.loader)
        for _ in range(2):
            next(it)
        t2.step(batch=next(it))
        t2.step(batch=next(it))
        final = jax.device_get(t2.params)
    finally:
        t2.close()
    final = dict(final, layers=deinterleave_stacked_params(
        final["layers"], 4, 2, 2))
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=2e-5),
        final, ref,
    )


@pytest.mark.slow
def test_checkpoint_restores_across_mesh_change(tmp_path):
    """Elastic resume: a checkpoint saved on a tp2xdp4 mesh restores onto
    a dp8 mesh (orbax re-shards to the restore templates) with identical
    global params, and training continues. The reference's per-rank .pth
    layout pins the topology — this is a TPU-native capability gain."""
    import jax

    from scaletorch_tpu.trainer.trainer import Trainer

    def cfg(**kw):
        return _cfg(checkpoint_dir=str(tmp_path), **kw)

    t1 = Trainer(cfg(tensor_parallel_size=2, data_parallel_size=4))
    try:
        t1.step()
        t1.step()
        saved = jax.device_get(t1.params)
        t1.save_checkpoint()
        t1._ckpt_mgr.wait()
    finally:
        t1.close()

    t2 = Trainer(cfg(tensor_parallel_size=1, data_parallel_size=8,
                     resume_from_checkpoint=True))
    try:
        t2.load_checkpoint()
        assert t2.global_step == 2
        restored = jax.device_get(t2.params)
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(a, b),
            restored, saved,
        )
        # and the re-sharded state actually trains on the new mesh
        m = t2.step()
        assert np.isfinite(float(m["loss"]))
    finally:
        t2.close()


@pytest.mark.slow
def test_load_checkpoint_resets_step_iterator(tmp_path):
    from scaletorch_tpu.trainer.trainer import Trainer

    t = Trainer(_cfg(checkpoint_dir=str(tmp_path)))
    try:
        t.step()
        t.step()
        assert t._train_iter is not None
        t.save_checkpoint()
        t._ckpt_mgr.wait()
        t.load_checkpoint()
        # the stale generator predates set_state and must be dropped
        assert t._train_iter is None
        assert t.global_step == 2
        m = t.step()  # next draw builds a fresh, fast-forwarded iterator
        assert np.isfinite(float(m["loss"]))
    finally:
        t.close()
