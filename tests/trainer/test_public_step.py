"""Trainer.step() — the public per-step API (ADVICE r4 #4).

Contracts under test (code-review r5): tokens_seen counts the batch
actually trained on (not the loader's nominal shape), and
load_checkpoint drops the persistent step() iterator so the
set_state fast-forward actually takes effect on the next draw.
"""

import numpy as np
import pytest

from scaletorch_tpu.config import ScaleTorchTPUArguments


def _cfg(**kw):
    return ScaleTorchTPUArguments(
        model_type="llama", hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        vocab_size=64, sequence_length=16, max_position_embeddings=32,
        data_parallel_size=8, micro_batch_size=1,
        gradient_accumulation_steps=2, synthetic_data=True,
        total_train_steps=8, dtype="float32", donate_params=False,
        log_frequency=100, **kw,
    )


@pytest.mark.slow
def test_step_counts_actual_batch_tokens():
    from scaletorch_tpu.trainer.trainer import Trainer

    t = Trainer(_cfg())
    try:
        m = t.step()  # draws from the loader
        assert np.isfinite(float(m["loss"]))
        assert t.global_step == 1
        assert t.tokens_seen == t.loader.tokens_per_step
        # caller-supplied batch with HALF the microbatches: accounting
        # must follow the batch, not the loader's nominal shape
        batch = next(iter(t.loader))
        half = {k: v[:1] for k, v in batch.items()}
        t.step(batch=half)
        assert t.tokens_seen == (
            t.loader.tokens_per_step + half["input_ids"].size
        )
    finally:
        t.close()


@pytest.mark.slow
def test_load_checkpoint_resets_step_iterator(tmp_path):
    from scaletorch_tpu.trainer.trainer import Trainer

    t = Trainer(_cfg(checkpoint_dir=str(tmp_path)))
    try:
        t.step()
        t.step()
        assert t._train_iter is not None
        t.save_checkpoint()
        t._ckpt_mgr.wait()
        t.load_checkpoint()
        # the stale generator predates set_state and must be dropped
        assert t._train_iter is None
        assert t.global_step == 2
        m = t.step()  # next draw builds a fresh, fast-forwarded iterator
        assert np.isfinite(float(m["loss"]))
    finally:
        t.close()
