"""Fault tolerance through the REAL 5D SPMD Trainer (slow tier).

The quick-tier harness (tests/test_resilience.py) proves the resilience
protocol on a mesh-free step; these goldens prove the same inject ->
recover contracts through the production path: shard_map step with the
in-jit non-finite guard, orbax checkpoints, loader fast-forward.
"""

import numpy as np
import pytest

from scaletorch_tpu.config import ScaleTorchTPUArguments


def _cfg(**kw):
    defaults = dict(
        model_type="llama", hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        vocab_size=64, sequence_length=16, max_position_embeddings=32,
        data_parallel_size=8, micro_batch_size=1,
        gradient_accumulation_steps=2, synthetic_data=True,
        total_train_steps=6, dtype="float32", donate_params=False,
        log_frequency=100, async_checkpointing=False,
        checkpoint_retry_base_delay=0.01, sentinel_frequency=1,
    )
    defaults.update(kw)
    return ScaleTorchTPUArguments(**defaults)


def _tokens(n=64, seq=16, vocab=64):
    return np.random.default_rng(5).integers(
        0, vocab, size=(n, seq + 1)).astype(np.int32)


def _use_file_loader(t, seed=11):
    """Swap the synthetic stream for a deterministic, resumable
    MicroBatchDataLoader (set_state support) — same pattern as the
    uneven-PP resume tests feed explicit batches."""
    from scaletorch_tpu.data.dataloader import MicroBatchDataLoader

    t.loader = MicroBatchDataLoader(
        _tokens(), micro_batch_size=t.cfg.micro_batch_size,
        gradient_accumulation_steps=t.cfg.gradient_accumulation_steps,
        data_parallel_size=t.cfg.data_parallel_size, seed=seed,
    )
    t._train_iter = None


@pytest.mark.slow
def test_spmd_nonfinite_guard_rejects_update():
    """NaN-poisoned params -> NaN loss inside the shard_map step -> the
    update is rejected in-jit: every param/opt leaf bit-identical,
    update_skipped reported."""
    import jax
    import jax.numpy as jnp

    from scaletorch_tpu.trainer.trainer import Trainer

    t = Trainer(_cfg())
    try:
        poisoned = dict(t.params)
        poisoned["final_norm"] = jax.tree.map(
            lambda x: (x * jnp.nan).astype(x.dtype), t.params["final_norm"])
        t.params = poisoned
        before = jax.device_get(t.params)
        opt_before = jax.device_get(t.opt_state)
        m = t.step()
        assert float(m["update_skipped"]) == 1.0
        after = jax.device_get(t.params)
        for a, b in zip(jax.tree.leaves(before), jax.tree.leaves(after)):
            np.testing.assert_array_equal(a, b)
        # float state (moments) frozen; integer schedule counts advance
        # so lr schedules stay aligned with global_step
        for a, b in zip(jax.tree.leaves(opt_before),
                        jax.tree.leaves(jax.device_get(t.opt_state))):
            if np.issubdtype(np.asarray(b).dtype, np.integer):
                np.testing.assert_array_equal(np.asarray(a) + 1, b)
            else:
                np.testing.assert_array_equal(a, b)
    finally:
        t.close()


@pytest.mark.slow
def test_injected_nan_skip_policy_trains_to_target(tmp_path):
    import jax

    from scaletorch_tpu.trainer.trainer import Trainer

    t = Trainer(_cfg(checkpoint_dir=str(tmp_path), save_frequency=2,
                     ft_nan_at_step=3, divergence_policy="skip"))
    try:
        t.train()
        assert t.global_step == 6
        assert t.resilience.counters()["nonfinite_losses"] == 1.0
        assert all(np.isfinite(np.asarray(x)).all()
                   for x in jax.tree.leaves(jax.device_get(t.params)))
    finally:
        t.close()


@pytest.mark.slow
def test_injected_nan_rollback_restores_checkpoint(tmp_path):
    from scaletorch_tpu.trainer.trainer import Trainer

    t = Trainer(_cfg(checkpoint_dir=str(tmp_path), save_frequency=2,
                     ft_nan_at_step=3, divergence_policy="rollback"))
    try:
        _use_file_loader(t)
        t.train()
        assert t.global_step == 6
        assert t.resilience.counters()["rollbacks"] == 1.0
    finally:
        t.close()


@pytest.mark.slow
def test_sigterm_emergency_checkpoint_resume_auto_matches(tmp_path):
    """Simulated preemption after step 3 -> emergency checkpoint -> a
    restarted Trainer with --resume auto semantics reaches the same
    final params as an uninterrupted run."""
    import jax

    from scaletorch_tpu.trainer.trainer import Trainer

    t_ref = Trainer(_cfg())
    try:
        _use_file_loader(t_ref)
        t_ref.train()
        ref = jax.device_get(t_ref.params)
    finally:
        t_ref.close()

    t1 = Trainer(_cfg(checkpoint_dir=str(tmp_path),
                      ft_sigterm_at_step=3))
    try:
        _use_file_loader(t1)
        t1.train()
        assert t1.preempted and t1.global_step == 3
        assert t1.checkpoint_manager.latest_step() == 3
    finally:
        t1.close()

    t2 = Trainer(_cfg(checkpoint_dir=str(tmp_path)))
    try:
        _use_file_loader(t2)
        assert t2.load_checkpoint()
        assert t2.global_step == 3
        t2.train()  # absolute target: continues to total_train_steps
        assert t2.global_step == 6
        final = jax.device_get(t2.params)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, atol=2e-5),
            ref, final,
        )
    finally:
        t2.close()


@pytest.mark.slow
def test_save_retries_complete_run_without_data_loss(tmp_path):
    from scaletorch_tpu.trainer.trainer import Trainer

    t = Trainer(_cfg(checkpoint_dir=str(tmp_path), save_frequency=2,
                     ft_fail_saves=2, checkpoint_retries=3))
    try:
        t.train()
        assert t.global_step == 6
        assert t.checkpoint_manager.all_steps() == [2, 4, 6]
    finally:
        t.close()
