"""Train-step semantics: grad accumulation, clipping, optimization."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scaletorch_tpu.config import ScaleTorchTPUArguments
from scaletorch_tpu.models.llama import LlamaConfig, forward, init_params
from scaletorch_tpu.trainer.optimizer import create_optimizer
from scaletorch_tpu.trainer.train_step import (
    accumulate_gradients,
    make_loss_fn,
    make_train_step,
)

CFG = LlamaConfig(
    vocab_size=64, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
    num_attention_heads=4, num_key_value_heads=2, dtype=jnp.float32,
)


def make_batch(accum, bs, seq=16, seed=0):
    rng = np.random.default_rng(seed)
    toks = rng.integers(0, CFG.vocab_size, size=(accum, bs, seq + 1), dtype=np.int32)
    return {
        "input_ids": jnp.asarray(toks[:, :, :-1]),
        "target_ids": jnp.asarray(toks[:, :, 1:]),
        "position_ids": jnp.broadcast_to(jnp.arange(seq, dtype=jnp.int32), (accum, seq)),
    }


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.PRNGKey(0), CFG)


class TestGradAccumulation:
    @pytest.mark.slow
    def test_accum_equals_big_batch(self, params):
        """no_sync contract: accumulating 4 microbatches of 1 == one
        microbatch of 4 (loss is a token mean; equal-size microbatches)."""
        loss_fn = make_loss_fn(forward, CFG, attention_backend="sdpa",
                               gradient_checkpointing=False)
        toks = make_batch(4, 1)
        big = {
            "input_ids": toks["input_ids"].reshape(1, 4, 16),
            "target_ids": toks["target_ids"].reshape(1, 4, 16),
            "position_ids": toks["position_ids"][:1],
        }
        loss_a, grads_a = accumulate_gradients(loss_fn, params, toks)
        loss_b, grads_b = accumulate_gradients(loss_fn, params, big)
        assert float(loss_a) == pytest.approx(float(loss_b), rel=1e-5)
        for a, b in zip(jax.tree.leaves(grads_a), jax.tree.leaves(grads_b)):
            np.testing.assert_allclose(a, b, atol=1e-5)

    def test_grads_are_fp32(self, params):
        loss_fn = make_loss_fn(forward, CFG, attention_backend="sdpa",
                               gradient_checkpointing=False)
        _, grads = accumulate_gradients(loss_fn, params, make_batch(2, 1))
        for g in jax.tree.leaves(grads):
            assert g.dtype == jnp.float32


class TestTrainStep:
    @pytest.mark.slow
    def test_memorizes_fixed_batch(self, params):
        args = ScaleTorchTPUArguments(total_train_steps=40, learning_rate=3e-3)
        tx, _ = create_optimizer(args)
        opt_state = tx.init(params)
        step = make_train_step(forward, CFG, tx, donate=False)
        batch = make_batch(1, 2)
        p = params
        first = None
        for i in range(30):
            p, opt_state, m = step(p, opt_state, batch)
            if first is None:
                first = float(m["loss"])
        assert float(m["loss"]) < 0.5 * first

    def test_metrics_contract(self, params):
        args = ScaleTorchTPUArguments(total_train_steps=10)
        tx, _ = create_optimizer(args)
        step = make_train_step(forward, CFG, tx, donate=False)
        _, _, m = step(params, tx.init(params), make_batch(2, 1))
        assert set(m) == {"loss", "grad_norm", "update_skipped"}
        assert float(m["grad_norm"]) > 0
        assert float(m["update_skipped"]) == 0.0

    def test_grad_clipping_bounds_update(self, params):
        """With max_grad_norm tiny, the applied update must be bounded."""
        args = ScaleTorchTPUArguments(
            total_train_steps=10, learning_rate=1.0, max_grad_norm=1e-6,
            optimizer_name="sgd", warmup_steps=0,
        )
        tx, _ = create_optimizer(args)
        step = make_train_step(forward, CFG, tx, donate=False)
        p2, _, _ = step(params, tx.init(params), make_batch(1, 1))
        diffs = [
            float(jnp.abs(a - b).max())
            for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(p2))
        ]
        assert max(diffs) < 1e-5


class TestOptimizers:
    @pytest.mark.parametrize("name", [
        "adamw",
        pytest.param("adam", marks=pytest.mark.slow),
        pytest.param("sgd", marks=pytest.mark.slow),
        pytest.param("lamb", marks=pytest.mark.slow),
        pytest.param("adafactor", marks=pytest.mark.slow),
    ])
    def test_all_optimizers_step(self, params, name):
        args = ScaleTorchTPUArguments(
            total_train_steps=10, optimizer_name=name, learning_rate=1e-3
        )
        tx, _ = create_optimizer(args)
        step = make_train_step(forward, CFG, tx, donate=False)
        p2, _, m = step(params, tx.init(params), make_batch(1, 1))
        assert np.isfinite(float(m["loss"]))
        # params changed
        changed = any(
            not np.allclose(a, b)
            for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(p2))
        )
        assert changed

    def test_unknown_optimizer(self):
        args = ScaleTorchTPUArguments(optimizer_name="zeus")
        with pytest.raises(ValueError, match="unknown optimizer"):
            create_optimizer(args)


@pytest.mark.slow
def test_uneven_pp_checkpoint_resume(tmp_path):
    """Save/resume with a PADDED uneven-PP layer stack: the orbax tree
    round-trips the padded layout and the resumed run continues exactly
    where the continuous run would be."""
    import jax

    from scaletorch_tpu.trainer.trainer import Trainer

    def cfg(**kw):
        return ScaleTorchTPUArguments(
            model_type="llama", hidden_size=32, intermediate_size=64,
            num_hidden_layers=3, num_attention_heads=4,
            num_key_value_heads=2, vocab_size=64, sequence_length=16,
            max_position_embeddings=32,
            pipeline_parallel_size=2, data_parallel_size=4,
            micro_batch_size=4, synthetic_data=True,
            total_train_steps=4, dtype="float32", donate_params=False,
            log_frequency=100, checkpoint_dir=str(tmp_path), **kw,
        )

    # run 2 steps, SAVE mid-run, keep going to 4 — the continued half
    # doubles as the ground truth (saving perturbs no training state)
    t1 = Trainer(cfg())
    it = iter(t1.loader)
    losses = []
    for step in range(4):
        b = t1._device_batch(next(it))
        t1.params, t1.opt_state, m = t1.step_fn(t1.params, t1.opt_state, b)
        t1.global_step += 1
        losses.append(float(m["loss"]))
        if step == 1:
            t1.tokens_seen = t1.global_step * t1.loader.tokens_per_step
            fp_before = [float(jnp.sum(x)) for x in
                         jax.tree_util.tree_leaves(t1.params)]
            t1.save_checkpoint()
            t1._ckpt_mgr.wait()
            # the continued half doubles as the ground truth ONLY if the
            # save left training state untouched — assert it, don't assume
            fp_after = [float(jnp.sum(x)) for x in
                        jax.tree_util.tree_leaves(t1.params)]
            assert fp_before == fp_after
    t1._ckpt_mgr.wait()
    t1.close()

    t2 = Trainer(cfg(resume_from_checkpoint=True))
    t2.load_checkpoint()  # train.py:31-32 drives this (reference parity)
    assert t2.global_step == 2
    # padded stacked shape survived the round trip
    lead = jax.tree_util.tree_leaves(t2.params["layers"])[0].shape[0]
    assert lead == 4  # 3 layers padded to 2 slots x pp=2
    it = iter(t2.loader)
    for _ in range(2):
        next(it)  # synthetic stream has no set_state; skip consumed steps
    resumed = []
    for _ in range(2):
        b = t2._device_batch(next(it))
        t2.params, t2.opt_state, m = t2.step_fn(t2.params, t2.opt_state, b)
        t2.global_step += 1
        resumed.append(float(m["loss"]))
    t2.close()
    assert resumed == pytest.approx(losses[2:], rel=1e-5)
