"""System diagnostics (utils/env_info.py, reference env_utils.py parity)."""

from __future__ import annotations

import logging

from scaletorch_tpu.utils.env_info import get_system_info, log_system_info


def test_get_system_info_core_fields():
    info = get_system_info()
    for key in ("Operating System", "Python Version", "CPU Count",
                "Memory Total", "Hostname", "Device Type", "Device Count",
                "JAX Version"):
        assert key in info, key
    assert info["Device Count"] >= 1
    assert info["BF16 Support"] is True


def test_log_system_info_emits_lines(caplog):
    logger = logging.getLogger("env_info_test")
    with caplog.at_level(logging.INFO, logger="env_info_test"):
        info = log_system_info(logger)
    assert "System Diagnostic Information:" in caplog.text
    assert str(info["Device Count"]) in caplog.text
