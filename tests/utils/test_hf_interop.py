"""HF interop goldens: our forward must match transformers' logits.

The reference verifies weight loading by size sweeps + forward checks
(tools/verify_qwen3.py); here the check is end-to-end numeric: build a
tiny HF model with transformers (torch CPU), save safetensors, load with
load_hf_params, and compare logits token-for-token. Also round-trips
save_hf_params back into transformers.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")

from scaletorch_tpu.models.llama import LlamaConfig, forward  # noqa: E402
from scaletorch_tpu.models.qwen3 import Qwen3Config  # noqa: E402
from scaletorch_tpu.utils.hf_interop import (  # noqa: E402
    hf_checkpoint_layer_names,
    load_hf_params,
    save_hf_params,
)


def _tiny_hf_llama(tmp_path):
    hf_cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rope_theta=10000.0, rms_norm_eps=1e-6,
        tie_word_embeddings=False, attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(hf_cfg).eval()
    path = str(tmp_path / "llama")
    model.save_pretrained(path, safe_serialization=True)
    return model, hf_cfg, path


def _tiny_hf_qwen3(tmp_path):
    hf_cfg = transformers.Qwen3Config(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, max_position_embeddings=128, rope_theta=10000.0,
        rms_norm_eps=1e-6, tie_word_embeddings=True,
        attn_implementation="eager",
    )
    torch.manual_seed(1)
    model = transformers.Qwen3ForCausalLM(hf_cfg).eval()
    path = str(tmp_path / "qwen3")
    model.save_pretrained(path, safe_serialization=True)
    return model, hf_cfg, path


def _hf_logits(model, ids):
    with torch.no_grad():
        return model(torch.from_numpy(np.asarray(ids))).logits.float().numpy()


class TestLoadHF:
    @pytest.mark.slow
    def test_llama_logits_match(self, tmp_path):
        model, hf_cfg, path = _tiny_hf_llama(tmp_path)
        cfg = LlamaConfig.from_hf(hf_cfg, dtype=jnp.float32)
        params = load_hf_params(path, cfg)
        ids = np.arange(2 * 16, dtype=np.int32).reshape(2, 16) % cfg.vocab_size
        ours = np.asarray(forward(params, ids, cfg))
        theirs = _hf_logits(model, ids)
        np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)

    @pytest.mark.slow
    def test_qwen3_logits_match(self, tmp_path):
        model, hf_cfg, path = _tiny_hf_qwen3(tmp_path)
        cfg = Qwen3Config.from_hf(hf_cfg, dtype=jnp.float32)
        assert cfg.tie_word_embeddings and cfg.qk_norm
        params = load_hf_params(path, cfg)
        assert "lm_head" not in params
        ids = np.arange(2 * 16, dtype=np.int32).reshape(2, 16) % cfg.vocab_size
        ours = np.asarray(forward(params, ids, cfg))
        theirs = _hf_logits(model, ids)
        np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)

    @pytest.mark.slow  # TestStreamedLoad covers the sharded-load contract
    def test_load_into_shardings(self, tmp_path):
        from jax.sharding import NamedSharding
        from scaletorch_tpu.parallel.mesh import MeshManager
        from scaletorch_tpu.parallel.tensor_parallel import llama_param_specs

        model, hf_cfg, path = _tiny_hf_llama(tmp_path)
        cfg = LlamaConfig.from_hf(hf_cfg, dtype=jnp.float32)
        from jax.sharding import PartitionSpec as P

        mm = MeshManager(tp=2, dp=4)
        specs = llama_param_specs(cfg, tp_axis="tp")
        shardings = jax.tree.map(
            lambda s: NamedSharding(mm.mesh, s), specs,
            is_leaf=lambda x: isinstance(x, P),
        )
        params = load_hf_params(path, cfg, shardings=shardings)
        q = params["layers"]["q_proj"]
        assert q.sharding.spec == specs["layers"]["q_proj"]

    def test_layer_names_enumeration(self, tmp_path):
        _, _, path = _tiny_hf_llama(tmp_path)
        by_layer = hf_checkpoint_layer_names(path)
        assert sorted(by_layer) == [0, 1]
        assert any("q_proj" in n for n in by_layer[0])

    def test_missing_tensor_raises(self, tmp_path):
        _, hf_cfg, path = _tiny_hf_llama(tmp_path)
        cfg = LlamaConfig.from_hf(hf_cfg, num_hidden_layers=4,
                                  dtype=jnp.float32)  # more layers than ckpt
        with pytest.raises(KeyError, match="not found"):
            load_hf_params(path, cfg)


class TestQuickRoundTrip:
    """Quick-tier save_hf_params -> load_hf_params round-trips (no HF
    model in the loop — pure safetensors I/O). The decode engine consumes
    exactly this export path (ISSUE 4), so the contract needs coverage
    that runs on every push, not just the slow-tier HF-logit goldens."""

    def test_llama_round_trip_exact(self, tmp_path):
        cfg = LlamaConfig(
            vocab_size=64, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, dtype=jnp.float32,
            tie_word_embeddings=False,
        )
        from scaletorch_tpu.models.llama import init_params

        params = init_params(jax.random.PRNGKey(0), cfg)
        out = save_hf_params(str(tmp_path / "rt"), params, cfg)
        assert out.endswith("model.safetensors")
        reloaded = load_hf_params(str(tmp_path / "rt"), cfg)
        assert set(reloaded) == set(params)
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)),
            params, reloaded,
        )

    def test_qwen3_tied_round_trip(self, tmp_path):
        cfg = Qwen3Config(
            vocab_size=64, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, head_dim=16, dtype=jnp.float32,
        )
        from scaletorch_tpu.models.qwen3 import init_params

        params = init_params(jax.random.PRNGKey(1), cfg)
        assert "lm_head" not in params  # tied
        save_hf_params(str(tmp_path / "rt_q3"), params, cfg)
        reloaded = load_hf_params(str(tmp_path / "rt_q3"), cfg)
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)),
            params, reloaded,
        )

    def test_round_trip_feeds_decode_engine(self, tmp_path):
        """Export -> reload -> serve: the engine's logits off reloaded
        params match the originals (the serving hand-off the ISSUE
        names: hf_interop weights feed the engine directly)."""
        from scaletorch_tpu.inference.decode import teacher_forced_decode
        from scaletorch_tpu.models.llama import forward as llama_forward
        from scaletorch_tpu.models.llama import init_params

        cfg = LlamaConfig(
            vocab_size=64, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, dtype=jnp.float32,
            tie_word_embeddings=False,
        )
        params = init_params(jax.random.PRNGKey(2), cfg)
        save_hf_params(str(tmp_path / "serve"), params, cfg)
        reloaded = load_hf_params(str(tmp_path / "serve"), cfg)
        ids = np.arange(2 * 8, dtype=np.int32).reshape(2, 8) % cfg.vocab_size
        full = np.asarray(llama_forward(params, ids, cfg))
        served = np.asarray(teacher_forced_decode(
            reloaded, cfg, jnp.asarray(ids), max_seq=8, prefill_len=3))
        np.testing.assert_allclose(served, full, atol=2e-5)


class TestSaveHF:
    def test_round_trip_through_transformers(self, tmp_path):
        model, hf_cfg, path = _tiny_hf_llama(tmp_path)
        cfg = LlamaConfig.from_hf(hf_cfg, dtype=jnp.float32)
        params = load_hf_params(path, cfg)

        out_dir = str(tmp_path / "exported")
        save_hf_params(out_dir, params, cfg)
        hf_cfg.save_pretrained(out_dir)
        reloaded = transformers.LlamaForCausalLM.from_pretrained(
            out_dir, attn_implementation="eager"
        ).eval()

        ids = np.arange(2 * 12, dtype=np.int32).reshape(2, 12) % cfg.vocab_size
        np.testing.assert_allclose(
            _hf_logits(reloaded, ids), _hf_logits(model, ids),
            rtol=1e-5, atol=1e-5,
        )


class TestStreamedLoad:
    """VERDICT r1 weak #4: sharded loading must stream — bounded host
    memory — and match the host-assembled load exactly."""

    def test_streamed_matches_host_load(self, tmp_path):
        from jax.sharding import NamedSharding, PartitionSpec as P
        from scaletorch_tpu.parallel.mesh import MeshManager
        from scaletorch_tpu.parallel.tensor_parallel import llama_param_specs

        model, hf_cfg, path = _tiny_hf_llama(tmp_path)
        cfg = LlamaConfig.from_hf(hf_cfg, dtype=jnp.float32)
        host = load_hf_params(path, cfg)

        mm = MeshManager(tp=2, pp=2, dp=2)
        specs = llama_param_specs(cfg, tp_axis="tp", pp_axis="pp")
        shardings = jax.tree.map(
            lambda s: NamedSharding(mm.mesh, s), specs,
            is_leaf=lambda x: isinstance(x, P),
        )
        streamed = load_hf_params(path, cfg, shardings=shardings)
        assert streamed["layers"]["q_proj"].sharding.spec == \
            specs["layers"]["q_proj"]
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b))
            ),
            host, streamed,
        )

    def test_streamed_reads_are_bounded(self, tmp_path, monkeypatch):
        """No single checkpoint read may materialise more than one
        (sliced) layer tensor — the bounded-host-memory contract."""
        import scaletorch_tpu.utils.hf_interop as interop
        from jax.sharding import NamedSharding, PartitionSpec as P
        from scaletorch_tpu.parallel.mesh import MeshManager
        from scaletorch_tpu.parallel.tensor_parallel import llama_param_specs

        model, hf_cfg, path = _tiny_hf_llama(tmp_path)
        cfg = LlamaConfig.from_hf(hf_cfg, dtype=jnp.float32)

        sizes = []
        real = interop._read_hf_slice

        def spy(handle, name, idx, transpose):
            t = real(handle, name, idx, transpose)
            sizes.append((name, t.nbytes))
            return t

        monkeypatch.setattr(interop, "_read_hf_slice", spy)

        mm = MeshManager(tp=2, pp=2, dp=2)
        specs = llama_param_specs(cfg, tp_axis="tp", pp_axis="pp")
        shardings = jax.tree.map(
            lambda s: NamedSharding(mm.mesh, s), specs,
            is_leaf=lambda x: isinstance(x, P),
        )
        load_hf_params(path, cfg, shardings=shardings)

        assert sizes, "spy never saw a read"
        # Largest single read <= largest single checkpoint tensor (the
        # embedding); layer tensors never arrive stacked.
        vocab_bytes = cfg.vocab_size * cfg.hidden_size * 4
        assert max(s for _, s in sizes) <= vocab_bytes
        # TP-sharded projections arrive pre-sliced: a q_proj read is at
        # most half (tp=2) the full tensor.
        q_full = cfg.hidden_size * (
            cfg.num_attention_heads * cfg.actual_head_dim) * 4
        q_reads = [s for n, s in sizes if "q_proj" in n]
        assert q_reads and max(q_reads) <= q_full // 2

    def test_streamed_moe_with_ep(self, tmp_path):
        from jax.sharding import NamedSharding, PartitionSpec as P
        from scaletorch_tpu.models.qwen3_moe import (
            Qwen3MoEConfig, init_params, qwen3_moe_param_specs,
        )
        from scaletorch_tpu.parallel.mesh import MeshManager

        cfg = Qwen3MoEConfig(
            vocab_size=128, hidden_size=32, intermediate_size=64,
            moe_intermediate_size=48, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2, head_dim=16,
            num_experts=4, num_experts_per_tok=2, dtype=jnp.float32,
            tie_word_embeddings=False,
        )
        params = init_params(jax.random.PRNGKey(0), cfg)
        path = str(tmp_path / "moe")
        save_hf_params(path, params, cfg)

        mm = MeshManager(ep=2, tp=2, dp=2)
        specs = qwen3_moe_param_specs(cfg, tp_axis="tp", ep_axis="ep")
        shardings = jax.tree.map(
            lambda s: NamedSharding(mm.mesh, s), specs,
            is_leaf=lambda x: isinstance(x, P),
        )
        streamed = load_hf_params(path, cfg, shardings=shardings)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(jax.device_get(a)),
                np.asarray(jax.device_get(b)), atol=1e-7,
            ),
            params, streamed,
        )


class TestShardedBf16Save:
    def test_bf16_sharded_round_trip(self, tmp_path):
        model, hf_cfg, path = _tiny_hf_llama(tmp_path)
        cfg = LlamaConfig.from_hf(hf_cfg, dtype=jnp.float32)
        params = load_hf_params(path, cfg)

        out_dir = str(tmp_path / "bf16_sharded")
        # Tiny shard budget forces the index + multi-file layout.
        result = save_hf_params(out_dir, params, cfg, dtype="bfloat16",
                                max_shard_bytes=4 * 1024)
        assert result.endswith("model.safetensors.index.json")
        import json as _json
        import os as _os

        with open(result) as f:
            index = _json.load(f)
        shard_files = set(index["weight_map"].values())
        assert len(shard_files) > 1
        for fname in shard_files:
            assert _os.path.exists(_os.path.join(out_dir, fname))

        reloaded = load_hf_params(out_dir, cfg)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-2, atol=1e-2
            ),
            params, reloaded,
        )

    def test_bf16_loads_in_transformers(self, tmp_path):
        model, hf_cfg, path = _tiny_hf_llama(tmp_path)
        cfg = LlamaConfig.from_hf(hf_cfg, dtype=jnp.float32)
        params = load_hf_params(path, cfg)

        out_dir = str(tmp_path / "bf16_hf")
        save_hf_params(out_dir, params, cfg, dtype="bfloat16")
        hf_cfg.save_pretrained(out_dir)
        reloaded = transformers.LlamaForCausalLM.from_pretrained(
            out_dir, attn_implementation="eager"
        ).eval()
        ids = np.arange(2 * 12, dtype=np.int32).reshape(2, 12) % cfg.vocab_size
        np.testing.assert_allclose(
            _hf_logits(reloaded, ids), _hf_logits(model, ids),
            rtol=5e-2, atol=5e-2,
        )


class TestInterleavedDenseMoE:
    """HF Qwen3-MoE variants with interleaved dense layers
    (mlp_only_layers / decoder_sparse_step) — VERDICT r3 missing #3. The
    reference's checkpoint mapping is generic over these configs
    (checkpoint.py:425-464); ours maps the per-kind layer stacks."""

    def _tiny_hf_moe(self, tmp_path, **cfg_kw):
        kw = dict(
            vocab_size=128, hidden_size=32, intermediate_size=64,
            moe_intermediate_size=48, num_hidden_layers=4,
            num_attention_heads=4, num_key_value_heads=2, head_dim=16,
            num_experts=4, num_experts_per_tok=2, norm_topk_prob=True,
            max_position_embeddings=128, rope_theta=10000.0,
            rms_norm_eps=1e-6, tie_word_embeddings=False,
            # layers 1, 3 sparse; 0, 2 dense (HF predicate)
            mlp_only_layers=[2], decoder_sparse_step=2,
            attn_implementation="eager",
        )
        kw.update(cfg_kw)
        hf_cfg = transformers.Qwen3MoeConfig(**kw)
        torch.manual_seed(3)
        model = transformers.Qwen3MoeForCausalLM(hf_cfg).eval()
        path = str(tmp_path / "moe_mixed")
        model.save_pretrained(path, safe_serialization=True)
        return model, hf_cfg, path

    def test_layout_predicate_matches_hf_modules(self, tmp_path):
        from scaletorch_tpu.models.qwen3_moe import Qwen3MoEConfig

        model, hf_cfg, _ = self._tiny_hf_moe(tmp_path)
        cfg = Qwen3MoEConfig.from_hf(hf_cfg, dtype=jnp.float32)
        hf_kinds = tuple(
            type(layer.mlp).__name__ == "Qwen3MoeSparseMoeBlock"
            for layer in model.model.layers
        )
        assert cfg.sparse_layout() == hf_kinds
        # explicit: (i+1) % 2 == 0 and i != 2  ->  layers 1, 3
        assert cfg.sparse_layer_ids() == (1, 3)
        assert cfg.dense_layer_ids() == (0, 2)
        assert cfg.moe_segments() == (
            (False, 0, 1), (True, 1, 2), (False, 2, 3), (True, 3, 4))

    @pytest.mark.slow
    def test_logits_match_hf(self, tmp_path):
        from scaletorch_tpu.models.qwen3_moe import Qwen3MoEConfig, forward

        model, hf_cfg, path = self._tiny_hf_moe(tmp_path)
        # capacity_factor = E/k makes capacity == S: zero drops, so the
        # capacity path computes exactly what HF's dropless MoE computes
        cfg = Qwen3MoEConfig.from_hf(
            hf_cfg, dtype=jnp.float32, capacity_factor=2.0)
        params = load_hf_params(path, cfg)
        assert params["layers"]["router"].shape[0] == 2       # sparse subset
        assert params["layers"]["gate_proj"].shape[0] == 2    # dense subset
        ids = np.arange(2 * 16, dtype=np.int32).reshape(2, 16) % cfg.vocab_size
        ours = np.asarray(forward(params, ids, cfg))
        theirs = _hf_logits(model, ids)
        np.testing.assert_allclose(ours, theirs, rtol=5e-4, atol=5e-4)

    def test_round_trip_through_transformers(self, tmp_path):
        from scaletorch_tpu.models.qwen3_moe import Qwen3MoEConfig

        model, hf_cfg, path = self._tiny_hf_moe(tmp_path)
        cfg = Qwen3MoEConfig.from_hf(hf_cfg, dtype=jnp.float32)
        params = load_hf_params(path, cfg)
        out_dir = str(tmp_path / "exported_mixed")
        save_hf_params(out_dir, params, cfg)
        hf_cfg.save_pretrained(out_dir)
        reloaded = transformers.Qwen3MoeForCausalLM.from_pretrained(
            out_dir, attn_implementation="eager"
        ).eval()
        ids = np.arange(2 * 12, dtype=np.int32).reshape(2, 12) % cfg.vocab_size
        np.testing.assert_allclose(
            _hf_logits(reloaded, ids), _hf_logits(model, ids),
            rtol=1e-5, atol=1e-5,
        )

    def test_all_dense_config_rejected(self):
        from scaletorch_tpu.models.qwen3_moe import Qwen3MoEConfig

        with pytest.raises(ValueError, match="no layer is sparse"):
            Qwen3MoEConfig(
                num_hidden_layers=2, mlp_only_layers=(0, 1),
                vocab_size=64, hidden_size=32, intermediate_size=64,
                num_attention_heads=4, num_key_value_heads=2, head_dim=8,
            )


def test_save_rejects_padded_uneven_pp_tree(tmp_path):
    """A padded uneven-PP layer stack must not silently export pad rows
    as real layers — the pad layout is pp-dependent and needs explicit
    unpadding."""
    import jax

    from scaletorch_tpu.models.llama import LlamaConfig, init_params
    from scaletorch_tpu.parallel.pipeline_parallel import (
        pad_stacked_params,
        unpad_stacked_params,
    )

    cfg = LlamaConfig(
        vocab_size=64, hidden_size=16, intermediate_size=32,
        num_hidden_layers=3, num_attention_heads=2, num_key_value_heads=2,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    padded = dict(params, layers=pad_stacked_params(params["layers"], 3, 2))
    with pytest.raises(ValueError, match="unpad"):
        save_hf_params(str(tmp_path / "x"), padded, cfg)
    # and the documented fix round-trips
    fixed = dict(padded, layers=unpad_stacked_params(padded["layers"], 3, 2))
    save_hf_params(str(tmp_path / "ok"), fixed, cfg)


@pytest.mark.slow
def test_save_deinterleaves_interleaved_pp_tree(tmp_path):
    """pp_engine='interleaved' permutes the layer axis with UNCHANGED
    shape — invisible to any check, so the caller declares it via
    pp_interleaved and the export must equal the true-order export
    byte-for-byte."""
    import jax
    from safetensors import safe_open

    from scaletorch_tpu.models.llama import LlamaConfig, init_params
    from scaletorch_tpu.parallel.pipeline_parallel import (
        interleave_stacked_params,
    )

    cfg = LlamaConfig(
        vocab_size=64, hidden_size=16, intermediate_size=32,
        num_hidden_layers=4, num_attention_heads=2, num_key_value_heads=2,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    save_hf_params(str(tmp_path / "true"), params, cfg)
    inter = dict(params, layers=interleave_stacked_params(
        params["layers"], 4, 2, 2))
    save_hf_params(str(tmp_path / "decl"), inter, cfg, pp_interleaved=(2, 2))
    with safe_open(str(tmp_path / "true" / "model.safetensors"), "np") as a, \
            safe_open(str(tmp_path / "decl" / "model.safetensors"), "np") as b:
        assert set(a.keys()) == set(b.keys())
        for k in a.keys():
            np.testing.assert_array_equal(a.get_tensor(k), b.get_tensor(k))
