"""MFU math, formatting, device registry."""

import jax.numpy as jnp
import pytest

from scaletorch_tpu.utils.device import (
    get_theoretical_flops,
    register_device_flops,
)
from scaletorch_tpu.utils.misc import (
    get_flops_per_token,
    get_mfu,
    get_num_params,
    to_readable_format,
)


class TestReadableFormat:
    def test_scales(self):
        assert to_readable_format(1_234) == "1.23K"
        assert to_readable_format(1_234_567) == "1.23M"
        assert to_readable_format(1.5e9) == "1.50B"
        assert to_readable_format(2e12) == "2.00T"
        assert to_readable_format(42) == "42.00"


class TestMfu:
    def test_flops_per_token_formula(self):
        # Must match the reference formula 6N + 12·L·H·Dh·S (misc.py:171)
        # so MFU numbers are comparable with BASELINE.md.
        n, l, h, d, s = 600e6, 28, 16, 128, 4096
        assert get_flops_per_token(n, l, h, d, s) == 6 * n + 12 * l * h * d * s

    def test_mfu_env_override(self, monkeypatch):
        monkeypatch.setenv("SCALETORCH_TPU_DEVICE_FLOPS", "1e12")
        # 1 param model, no attention: 6 flops/token; 1e11 tok/s -> 6e11 flops
        mfu = get_mfu(1e11, 1, 0, 0, 0, 1)
        assert mfu == pytest.approx(60.0)

    def test_register_device_flops(self, monkeypatch):
        monkeypatch.delenv("SCALETORCH_TPU_DEVICE_FLOPS", raising=False)
        register_device_flops("cpu", 5e12)
        assert get_theoretical_flops() == 5e12
        register_device_flops("cpu", 1e12)  # restore


class TestNumParams:
    def test_counts_pytree(self):
        params = {"a": jnp.ones((2, 3)), "b": {"c": jnp.ones((4,))}}
        assert get_num_params(params) == 10
