"""utils/monitor.read_accelerator_environment — the platform-sensor
reader behind the ring buffer's accel_/hwmon_ fields.

Contracts under test (previously untested):

  * attribution — a hwmon chip whose ``name`` matches an accelerator
    driver reports ``accel_*``; any other chip (coretemp, an NVMe
    sensor) reports ``hwmon_*`` so a host CPU temperature can never
    masquerade as chip telemetry;
  * absent-never-fabricated — nothing exposed means ``{}``, not zeros;
  * unit scaling — hwmon millidegrees / microwatts to C / W,
    ``TPU_METRICS_DIR`` sidecar values passed through unscaled;
  * precedence — first source wins via ``setdefault`` (hwmon accel
    channels are not overwritten by the sidecar).
"""

import pytest

from scaletorch_tpu.utils.monitor import read_accelerator_environment


def _hwmon(tmp_path, idx, name, temp_milli=None, power_micro=None):
    d = tmp_path / f"hwmon{idx}"
    d.mkdir()
    (d / "name").write_text(f"{name}\n")
    if temp_milli is not None:
        (d / "temp1_input").write_text(f"{temp_milli}\n")
    if power_micro is not None:
        (d / "power1_average").write_text(f"{power_micro}\n")
    return d


@pytest.fixture(autouse=True)
def _no_ambient_sources(monkeypatch, tmp_path):
    """Isolate from the host: an empty fake sensor tree and no sidecar
    unless the test sets one."""
    monkeypatch.delenv("TPU_METRICS_DIR", raising=False)


def test_nothing_exposed_returns_empty(tmp_path):
    out = read_accelerator_environment(hwmon_glob=str(tmp_path / "hwmon*"))
    assert out == {}  # absent, never fabricated — no zero-filled fields


def test_accel_chip_attributed_as_accel(tmp_path):
    _hwmon(tmp_path, 0, "tpu_common", temp_milli=45500, power_micro=12_000_000)
    out = read_accelerator_environment(hwmon_glob=str(tmp_path / "hwmon*"))
    assert out == {"accel_temp_c": 45.5, "accel_power_w": 12.0}


@pytest.mark.parametrize("chip", ["apex", "npu_driver", "my-accel-0"])
def test_accelerator_name_variants_match(tmp_path, chip):
    _hwmon(tmp_path, 0, chip, temp_milli=30000)
    out = read_accelerator_environment(hwmon_glob=str(tmp_path / "hwmon*"))
    assert out == {"accel_temp_c": 30.0}


def test_host_sensor_never_masquerades_as_accel(tmp_path):
    _hwmon(tmp_path, 0, "coretemp", temp_milli=70000)
    _hwmon(tmp_path, 1, "nvme", temp_milli=40000, power_micro=3_000_000)
    out = read_accelerator_environment(hwmon_glob=str(tmp_path / "hwmon*"))
    assert "accel_temp_c" not in out and "accel_power_w" not in out
    # first chip in sorted order wins the hwmon_ slot (setdefault)
    assert out == {"hwmon_temp_c": 70.0, "hwmon_power_w": 3.0}


def test_mixed_chips_attribute_independently(tmp_path):
    _hwmon(tmp_path, 0, "coretemp", temp_milli=70000)
    _hwmon(tmp_path, 1, "tpu0", temp_milli=42000)
    out = read_accelerator_environment(hwmon_glob=str(tmp_path / "hwmon*"))
    assert out == {"hwmon_temp_c": 70.0, "accel_temp_c": 42.0}


def test_unreadable_name_degrades_to_hwmon(tmp_path):
    d = tmp_path / "hwmon0"
    d.mkdir()  # no name file at all
    (d / "temp1_input").write_text("50000\n")
    out = read_accelerator_environment(hwmon_glob=str(tmp_path / "hwmon*"))
    assert out == {"hwmon_temp_c": 50.0}


def test_garbage_sensor_values_are_skipped(tmp_path):
    _hwmon(tmp_path, 0, "tpu0")
    (tmp_path / "hwmon0" / "temp1_input").write_text("not-a-number\n")
    out = read_accelerator_environment(hwmon_glob=str(tmp_path / "hwmon*"))
    assert out == {}


def test_tpu_metrics_dir_sidecar(tmp_path, monkeypatch):
    sidecar = tmp_path / "sidecar"
    sidecar.mkdir()
    (sidecar / "power").write_text("198.5\n")
    (sidecar / "temp").write_text("61.25 extra tokens ignored\n")
    monkeypatch.setenv("TPU_METRICS_DIR", str(sidecar))
    out = read_accelerator_environment(
        hwmon_glob=str(tmp_path / "hwmon*"))  # no hwmon chips
    assert out == {"accel_power_w": 198.5, "accel_temp_c": 61.25}


def test_hwmon_accel_wins_over_sidecar(tmp_path, monkeypatch):
    """Precedence is setdefault: the kernel driver's reading stands;
    the sidecar only fills channels hwmon did not provide."""
    _hwmon(tmp_path, 0, "tpu0", temp_milli=42000)
    sidecar = tmp_path / "sidecar"
    sidecar.mkdir()
    (sidecar / "temp").write_text("99.0\n")
    (sidecar / "power").write_text("150.0\n")
    monkeypatch.setenv("TPU_METRICS_DIR", str(sidecar))
    out = read_accelerator_environment(hwmon_glob=str(tmp_path / "hwmon*"))
    assert out == {"accel_temp_c": 42.0, "accel_power_w": 150.0}


def test_empty_sidecar_dir_fabricates_nothing(tmp_path, monkeypatch):
    sidecar = tmp_path / "sidecar"
    sidecar.mkdir()
    monkeypatch.setenv("TPU_METRICS_DIR", str(sidecar))
    out = read_accelerator_environment(hwmon_glob=str(tmp_path / "hwmon*"))
    assert out == {}
