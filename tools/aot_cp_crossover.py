#!/usr/bin/env python
"""AOT wire-byte sweep of the ring-vs-ulysses CP backend crossover.

The CP backend used to be picked from a hand-tuned table
(docs/long_context.md §4); ``parallel/cp_select.resolve_cp_backend`` now
computes the choice from topology + geometry. This tool replaces the
table's guesswork with compiled evidence, the same way
``tools/aot_dispatch_crossover.py`` attests ``resolve_moe_dispatch``:
for each (cp, head-geometry, seq) topology it compiles the REAL spmd
train step on a virtual cp-mesh with BOTH backends and records the
collective wire bytes XLA actually emits
(analysis/hlo.collective_wire_bytes ring-cost model), plus
the resolver's verdict for that topology.

Two modes:

    python tools/aot_cp_crossover.py            # regenerate the JSON
        [--out AOT_CP_CROSSOVER.json] [--seq 4096]

    python tools/aot_cp_crossover.py --check    # CI smoke (pure python,
        # no compiles): the checked-in JSON's rows must reproduce under
        # today's resolver, and the docs-table scenarios must resolve to
        # their documented answers. Exit 0/1.

Compiles run on virtual CPU devices (``xla_force_host_platform_device_
count``) in a child process per point — no TPU, no libtpu, no network.

Caveat (same as the MoE tool): wire bytes are compile-time evidence;
ring hops overlap with per-hop compute where ulysses' all-to-alls are
exposed, so the resolver demands a >= 2x byte margin before leaving the
ring on ICI (cp_select.ICI_ULYSSES_BYTE_MARGIN). The on-chip word is
``tools/bench_cp_compare.py``.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

_CHILD_ENV = "_SCALETORCH_TPU_CP_XOVER_CHILD"

# (label, cp, hq, hkv, seq) — the topologies the docs table covered:
# GQA default (qwen3-ish 16/8), GQA at higher cp, MHA (head-heavy), and
# an extreme-sequence point.
TOPOLOGIES = [
    ("gqa_cp4", 4, 16, 8, 4096),
    ("gqa_cp8", 8, 16, 8, 4096),
    ("mha_cp4", 4, 16, 16, 4096),
    ("gqa_cp4_seq64k", 4, 16, 8, 65536),
]

# docs/long_context.md §4, one scenario per table row (cross-host has no
# virtual-mesh compile — process_index is uniform in one process — so it
# is asserted via the resolver's hop input, not a compiled row).
DOCS_TABLE_SCENARIOS = [
    dict(label="default_long_context", cp=4, hq=16, hkv=8, seq=8192,
         hops=0, expect="ring"),
    dict(label="many_kv_heads", cp=4, hq=16, hkv=16, seq=8192,
         hops=0, expect="ulysses"),
    dict(label="cross_host_dcn", cp=4, hq=16, hkv=8, seq=8192,
         hops=2, expect="ulysses"),
    dict(label="extreme_seq", cp=4, hq=16, hkv=8, seq=131072,
         hops=0, expect="ring"),
]


def _compile_point(cp: int, hq: int, hkv: int, seq: int,
                   backend: str) -> dict:
    """Child-side: compile the spmd train step on a cp-only virtual mesh
    and report its collective wire bytes."""
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={cp}"
    ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    import jax.numpy as jnp
    import optax

    import scaletorch_tpu  # noqa: F401 — compat backfill on old jax
    from scaletorch_tpu.analysis.hlo import collective_wire_bytes
    from scaletorch_tpu.config import ScaleTorchTPUArguments
    from scaletorch_tpu.models import llama
    from scaletorch_tpu.parallel.mesh import MeshManager
    from scaletorch_tpu.parallel.spmd import make_spmd_train_step
    from scaletorch_tpu.trainer.trainer import build_model_config

    head_dim = 16
    cfg = ScaleTorchTPUArguments(
        model_type="llama", vocab_size=512, hidden_size=hq * head_dim,
        intermediate_size=2 * hq * head_dim, num_hidden_layers=2,
        num_attention_heads=hq, num_key_value_heads=hkv, head_dim=head_dim,
        max_position_embeddings=2 * seq, sequence_length=seq,
        micro_batch_size=1, context_parallel_size=cp, synthetic_data=True,
        max_grad_norm=1.0, attention_backend=backend,
        gradient_checkpointing=True,
    )
    model_cfg = build_model_config(cfg)
    mm = MeshManager(cp=cp)
    params = jax.eval_shape(
        lambda: llama.init_params(jax.random.PRNGKey(0), model_cfg))
    tx = optax.sgd(1.0)
    step_fn, _, _ = make_spmd_train_step(
        mm, llama.forward, model_cfg, tx, params,
        attention_backend=backend, gradient_checkpointing=True,
        max_grad_norm=1.0, donate=False,
    )
    batch = {
        "input_ids": jax.ShapeDtypeStruct((1, 1, seq), jnp.int32),
        "target_ids": jax.ShapeDtypeStruct((1, 1, seq), jnp.int32),
        "position_ids": jax.ShapeDtypeStruct((1, seq), jnp.int32),
    }
    oshape = jax.eval_shape(tx.init, params)
    hlo = step_fn.lower(params, oshape, batch).compile().as_text()
    rep = collective_wire_bytes(hlo)
    # The CP exchange is what differs between backends; the gradient/loss
    # all-reduces are identical overhead on both sides and would dilute
    # the comparison (a 2.7x attention-exchange gap reads as 1.8x total).
    exchange = sum(b for (op, _), b in rep["by_op"].items()
                   if op != "all-reduce")
    return {
        "backend": backend,
        "wire_mb": round(rep["total"] / 1e6, 3),
        "cp_exchange_mb": round(exchange / 1e6, 3),
        "by_op": {f"{op}:{dt}": round(b / 1e6, 3)
                  for (op, dt), b in rep["by_op"].items()},
    }


def _resolve(cp, hq, hkv, seq, hops):
    from scaletorch_tpu.parallel.cp_select import resolve_cp_backend

    return resolve_cp_backend(
        "auto", None, cp=cp, num_q_heads=hq, num_kv_heads=hkv,
        seq_len=seq, cross_host_hops=hops,
    )


def run_sweep(args) -> None:
    env = dict(os.environ)
    rows = []
    for label, cp, hq, hkv, seq in TOPOLOGIES:
        seq = args.seq if args.seq and "seq" not in label else seq
        point = {"label": label, "cp": cp, "hq": hq, "hkv": hkv, "seq": seq}
        for backend in ("ring", "ulysses"):
            env[_CHILD_ENV] = f"{cp}:{hq}:{hkv}:{seq}:{backend}"
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                env=env, capture_output=True, text=True, timeout=2400,
                cwd=REPO,
            )
            lines = [ln for ln in proc.stdout.splitlines()
                     if ln.startswith("{")]
            if proc.returncode != 0 or not lines:
                point[backend] = {"error": proc.stderr.strip()[-300:]}
            else:
                point[backend] = json.loads(lines[-1])
            print(json.dumps({label: point[backend]}), flush=True)
        ok = ("error" not in point.get("ring", {})
              and "error" not in point.get("ulysses", {}))
        if ok:
            point["compiled_bytes_winner"] = (
                "ring"
                if point["ring"]["wire_mb"] <= point["ulysses"]["wire_mb"]
                else "ulysses")
            point["ulysses_byte_advantage"] = round(
                point["ring"]["wire_mb"]
                / max(point["ulysses"]["wire_mb"], 1e-9), 2)
            # the number the resolver's 2x margin is judged against:
            # ring-vs-ulysses on the CP exchange alone (see _compile_point)
            point["ulysses_exchange_advantage"] = round(
                point["ring"]["cp_exchange_mb"]
                / max(point["ulysses"]["cp_exchange_mb"], 1e-9), 2)
        choice = _resolve(cp, hq, hkv, seq, hops=0)
        point["resolved"] = choice.backend
        point["resolved_reason"] = choice.reason
        rows.append(point)

    out = {
        "note": ("compiled collective wire bytes (ring cost model over "
                 "HLO replica groups) per CP backend per topology; "
                 "'resolved' is cp_select.resolve_cp_backend's verdict "
                 "at 0 DCN hops. The resolver leaves the ICI ring only "
                 "at a >= 2x byte margin (hops overlap with compute); "
                 "cross-host is decided by the DCN hop count, exercised "
                 "in --check via DOCS_TABLE_SCENARIOS."),
        "rows": rows,
        "docs_table": [
            dict(s, resolved=_resolve(
                s["cp"], s["hq"], s["hkv"], s["seq"], s["hops"]).backend)
            for s in DOCS_TABLE_SCENARIOS
        ],
    }
    path = os.path.join(REPO, args.out)
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"written": args.out, "rows": len(rows)}))


def run_check(args) -> int:
    """CI smoke: no compiles — the checked-in JSON must reproduce under
    today's resolver, and the docs-table scenarios must resolve to their
    documented answers."""
    path = os.path.join(REPO, args.out)
    failures = []
    with open(path) as f:
        data = json.load(f)
    from scaletorch_tpu.parallel.cp_select import ICI_ULYSSES_BYTE_MARGIN

    for row in data.get("rows", []):
        choice = _resolve(row["cp"], row["hq"], row["hkv"], row["seq"],
                          hops=0)
        if choice.backend != row["resolved"]:
            failures.append(
                f"{row['label']}: resolver now says {choice.backend}, "
                f"JSON recorded {row['resolved']} — regenerate the JSON "
                "or fix the resolver")
        adv = row.get("ulysses_exchange_advantage")
        # An ulysses verdict must be backed by a compiled CP-exchange
        # advantage clearing the SAME margin the resolver demands of the
        # analytic model — anything weaker means the rule and evidence
        # disagree. (Ring verdicts may have adv >= margin: the extreme-
        # seq row is decided by memory, not bytes.)
        if (adv is not None and choice.backend == "ulysses"
                and adv < ICI_ULYSSES_BYTE_MARGIN
                and "byte" in choice.reason):
            failures.append(
                f"{row['label']}: resolver picks ulysses on the byte "
                f"rule but the compiled CP-exchange advantage is only "
                f"{adv}x < {ICI_ULYSSES_BYTE_MARGIN}x")
    for s in DOCS_TABLE_SCENARIOS:
        got = _resolve(s["cp"], s["hq"], s["hkv"], s["seq"], s["hops"])
        if got.backend != s["expect"]:
            failures.append(
                f"docs-table scenario {s['label']}: expected "
                f"{s['expect']}, resolver says {got.backend} "
                f"({got.reason})")
    if failures:
        for f_ in failures:
            print(f"CHECK FAIL: {f_}", file=sys.stderr)
        return 1
    print(json.dumps({
        "check": "ok",
        "rows": len(data.get("rows", [])),
        "docs_table_scenarios": len(DOCS_TABLE_SCENARIOS),
    }))
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="AOT_CP_CROSSOVER.json")
    ap.add_argument("--seq", type=int, default=0,
                    help="override the non-extreme topologies' seq")
    ap.add_argument("--check", action="store_true",
                    help="validate the checked-in JSON against the "
                         "resolver (no compiles; CI smoke)")
    args = ap.parse_args()

    if os.environ.get(_CHILD_ENV):
        cp, hq, hkv, seq, backend = os.environ[_CHILD_ENV].split(":")
        print(json.dumps(_compile_point(
            int(cp), int(hq), int(hkv), int(seq), backend)))
        return 0
    if args.check:
        return run_check(args)
    run_sweep(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
