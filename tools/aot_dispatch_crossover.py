#!/usr/bin/env python
"""AOT cost-analysis sweep of the einsum-vs-index MoE dispatch crossover.

VERDICT r4 weak #3: the ``auto`` dispatch mode's E>16 threshold was a
guess. This tool replaces the guess with compiler truth: for each expert
count it AOT-compiles the REAL train step (local libtpu, v5e target, no
chip needed) in both dispatch forms and records XLA's own cost analysis
(total step FLOPs) plus the compiled temp-HBM. The crossover is the
smallest E where the index form's compiled FLOPs drop below the
einsum form's.

This is compile-time evidence, not wall-clock — scatter/gather can be
memory-bound where einsum is MXU-bound, so the on-chip A/B
(``python bench.py`` phase 3.5 / tools/bench_moe_dispatch.py) remains
the final word. Until a chip is reachable, the compiled-FLOP crossover
is the best available setting for ``resolve_moe_dispatch``.

Usage:
    python tools/aot_dispatch_crossover.py \
        [--experts 4 8 16 32 64] [--top-k 2] [--out AOT_DISPATCH_CROSSOVER.json]

Model shape: a 2-layer slice of the moe-mid geometry (hidden 1024,
expert FFN 384, seq 4096) — per-layer dispatch cost scales linearly in
depth, so 2 layers compile fast while preserving the FLOP *ratio*.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

_CHILD_ENV = "_SCALETORCH_TPU_XOVER_CHILD"


def _compile_point(num_experts: int, top_k: int, mode: str, seq: int) -> dict:
    """Child-side: lower + compile one (E, mode) point, return cost rows."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import topologies

    from scaletorch_tpu.config import ScaleTorchTPUArguments
    from scaletorch_tpu.models import qwen3_moe
    from scaletorch_tpu.parallel.mesh import MeshManager
    from scaletorch_tpu.parallel.spmd import make_spmd_train_step
    from scaletorch_tpu.trainer.optimizer import create_optimizer
    from scaletorch_tpu.trainer.trainer import build_model_config

    cfg = ScaleTorchTPUArguments(
        model_type="qwen3_moe", vocab_size=32768, hidden_size=1024,
        intermediate_size=3072, moe_intermediate_size=384,
        num_hidden_layers=2, num_attention_heads=16, num_key_value_heads=4,
        head_dim=64, rope_theta=1e6, max_position_embeddings=2 * seq,
        num_experts=num_experts, num_experts_per_tok=top_k,
        moe_dispatch=mode, sequence_length=seq, micro_batch_size=1,
        gradient_checkpointing=True, synthetic_data=True,
        dtype="bfloat16", max_grad_norm=1.0,
    )
    model_cfg = build_model_config(cfg)
    topo = topologies.get_topology_desc(platform="tpu",
                                        topology_name="v5e:2x2x1")
    mm = MeshManager(devices=list(topo.devices[:1]))
    params = jax.eval_shape(
        lambda: qwen3_moe.init_params(jax.random.key(0), model_cfg))
    specs = qwen3_moe.qwen3_moe_param_specs(model_cfg, tp_axis="tp")
    tx, _ = create_optimizer(cfg, include_clip=False)
    step_fn, _, _ = make_spmd_train_step(
        mm, qwen3_moe.forward, model_cfg, tx, params,
        gradient_checkpointing=True, max_grad_norm=1.0,
        param_specs=specs, model_family="qwen3_moe",
    )
    batch = {
        "input_ids": jax.ShapeDtypeStruct((1, 1, seq), jnp.int32),
        "target_ids": jax.ShapeDtypeStruct((1, 1, seq), jnp.int32),
        "position_ids": jax.ShapeDtypeStruct((1, seq), jnp.int32),
    }
    compiled = step_fn.lower(params, jax.eval_shape(tx.init, params),
                             batch).compile()
    m = compiled.memory_analysis()
    cost = compiled.cost_analysis() or {}
    return {
        "num_experts": num_experts, "top_k": top_k, "mode": mode,
        "step_tflops": round((cost.get("flops") or 0) / 1e12, 3),
        "temp_gb": round(m.temp_size_in_bytes / 1e9, 3),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--experts", nargs="*", type=int,
                    default=[4, 8, 16, 32, 64])
    ap.add_argument("--top-k", type=int, default=2)
    ap.add_argument("--seq", type=int, default=4096)
    ap.add_argument("--out", default="AOT_DISPATCH_CROSSOVER.json")
    args = ap.parse_args()

    if os.environ.get(_CHILD_ENV):
        e, k, mode, seq = os.environ[_CHILD_ENV].split(":")
        print(json.dumps(_compile_point(int(e), int(k), mode, int(seq))))
        return

    # scrubbed AOT env (the aot_memory.py recipe): local libtpu compiles
    # for v5e with no device attached and no axon tunnel in the way
    env = dict(os.environ, PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="",
               TPU_WORKER_HOSTNAMES="localhost", TPU_SKIP_MDS_QUERY="1")
    rows = []
    for e in args.experts:
        for mode in ("einsum", "index"):
            env[_CHILD_ENV] = f"{e}:{args.top_k}:{mode}:{args.seq}"
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                env=env, capture_output=True, text=True, timeout=2400,
                cwd=REPO,
            )
            line = [ln for ln in proc.stdout.splitlines()
                    if ln.startswith("{")]
            if proc.returncode != 0 or not line:
                rows.append({"num_experts": e, "mode": mode,
                             "error": proc.stderr.strip()[-300:]})
            else:
                rows.append(json.loads(line[-1]))
            print(json.dumps(rows[-1]), flush=True)

    # the crossover: smallest E where index compiles fewer FLOPs
    by_e: dict = {}
    for r in rows:
        if "error" not in r:
            by_e.setdefault(r["num_experts"], {})[r["mode"]] = r
    crossover = None
    for e in sorted(by_e):
        pair = by_e[e]
        if ("einsum" in pair and "index" in pair
                and pair["index"]["step_tflops"] < pair["einsum"]["step_tflops"]):
            crossover = e
            break
    out = {
        "top_k": args.top_k, "seq": args.seq, "rows": rows,
        "compiled_flops_crossover_experts": crossover,
        "note": ("index wins (fewer compiled step FLOPs) from this expert "
                 "count on; wall-clock confirmation: bench.py phase 3.5"),
    }
    print(json.dumps({"crossover": crossover}))
    with open(os.path.join(REPO, args.out), "w") as f:
        json.dump(out, f, indent=1)


if __name__ == "__main__":
    main()
