#!/usr/bin/env python
"""AOT HBM analyzer: compile a train-step for a target TPU gen with NO
device attached and report the compiler's exact memory accounting.

TPU-native counterpart of the reference's trial-and-error OOM probing
(scripts/benchmark_comprehensive.py catches torch.cuda OOM at runtime;
tools/optimize_mfu.py re-runs variants until one fits): XLA knows the
peak HBM of a compiled program before it ever touches a chip, so memory
feasibility is a compile-time query. Uses the local ``libtpu`` AOT
plugin via ``jax.experimental.topologies`` — works on a CPU-only box.

Usage:
    python tools/aot_memory.py --model qwen3-0.6b --seq 2048 --bs 2
    python tools/aot_memory.py --model qwen3-0.6b --seq 8192 --gc \\
        --policies nothing_saveable dots_saveable save_attn
    python tools/aot_memory.py --model qwen3-1.7b --seq 2048 --sweep-gc

Prints one JSON line per variant: argument/temp/output/alias bytes,
estimated peak HBM, and fits_hbm for the generation's per-chip HBM.
The accounting itself (argument/temp/alias/peak math) is shared with
the jaxlint memory tier (``scaletorch_tpu/analysis/memory.py``), which
gates the same numbers for the audit manifest in CI against
``tools/hbm_budget.json``; this tool keeps the libtpu AOT topology
path so the numbers come out for a real TPU generation.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

_CHILD_ENV = "_SCALETORCH_TPU_AOT_CHILD"

# Per-chip HBM by generation (utils/device.py carries FLOPS; memory here).
HBM_GB = {"v5e": 16, "v6e": 32, "v5p": 95, "v4": 32}


def _reexec_clean(argv: list[str]) -> int:
    """Re-exec in a subprocess with the axon tunnel env scrubbed so the
    local libtpu AOT plugin (not the remote-execution plugin) registers."""
    env = dict(os.environ)
    env[_CHILD_ENV] = "1"
    env.pop("JAX_PLATFORMS", None)
    env["PALLAS_AXON_POOL_IPS"] = ""  # sitecustomize skips axon register
    env.setdefault("TPU_WORKER_HOSTNAMES", "localhost")
    env.setdefault("TPU_SKIP_MDS_QUERY", "1")
    # No local devices in a compile-only session — device sniffing can't
    # see the TPU target, so force the Pallas kernels on explicitly.
    env.setdefault("SCALETORCH_TPU_FORCE_PALLAS", "1")
    proc = subprocess.run([sys.executable, os.path.abspath(__file__)] + argv,
                          env=env, cwd=REPO)
    return proc.returncode


def build_lowered(model: str, *, seq: int, micro_bs: int, grad_accum: int,
                  gc: bool, remat_policy: str, gen: str,
                  param_dtype: str = "float32", optimizer: str = "adamw",
                  dp: int = 1, tp: int = 1, cp: int = 1, pp: int = 1,
                  ep: int = 1, sp: bool = False, pp_engine: str = "afab",
                  pp_vpp: int = 1, moe_dispatch: str = "auto"):
    """Lower the real SPMD train step against an AOT TPU topology —
    single chip by default, or a multi-chip mesh factoring (dp/tp/cp/pp/
    ep over the 4-chip v5e host topology): Mosaic kernel compilation for
    sharded shapes and collective lowering onto ICI are validated without
    any hardware attached."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import topologies

    from scaletorch_tpu.benchmark import make_bench_args
    from scaletorch_tpu.models import llama, qwen3_moe
    from scaletorch_tpu.models.registry import resolve_attention_backend
    from scaletorch_tpu.parallel.mesh import MeshManager
    from scaletorch_tpu.parallel.spmd import make_spmd_train_step
    from scaletorch_tpu.trainer.optimizer import create_optimizer
    from scaletorch_tpu.trainer.trainer import build_model_config

    world = dp * tp * cp * pp * ep
    # smallest AOT topology that holds the mesh (v5e slices are 2D grids;
    # 4 chips = one host, 8/16 = multi-host slices — ICI collective
    # lowering is validated either way)
    for shape, n in (("2x2x1", 4), ("2x4x1", 8), ("4x4x1", 16),
                     ("4x8x1", 32)):
        if world <= n:
            topo = topologies.get_topology_desc(
                platform="tpu", topology_name=f"{gen}:{shape}")
            break
    else:
        raise ValueError(f"mesh {world} devices > largest AOT topology (32)")
    cfg = make_bench_args(model, seq=seq, micro_bs=micro_bs,
                          grad_accum=grad_accum, gc=gc,
                          remat_policy=remat_policy,
                          dp=dp, tp=tp, cp=cp, pp=pp, ep=ep, sp=sp,
                          pp_engine=pp_engine,
                          extra={"param_dtype": param_dtype,
                                 "optimizer_name": optimizer,
                                 "moe_dispatch": moe_dispatch,
                                 "pp_virtual_stages": pp_vpp})
    model_cfg = build_model_config(cfg)
    mm = MeshManager(devices=list(topo.devices[:world]),
                     dp=dp, pp=pp, cp=cp, ep=ep, tp=tp)

    is_moe = cfg.model_type == "qwen3_moe"
    mod = qwen3_moe if is_moe else llama
    params = jax.eval_shape(lambda: mod.init_params(jax.random.key(0), model_cfg))
    if pp > 1 and model_cfg.num_hidden_layers % pp:
        # Mirror the Trainer's uneven-PP padding so the HBM estimate
        # covers the padded slots the real run carries.
        from scaletorch_tpu.parallel.pipeline_parallel import pad_stacked_params

        params = dict(params, layers=jax.eval_shape(
            lambda t: pad_stacked_params(
                t, model_cfg.num_hidden_layers, pp),
            params["layers"],
        ))
    moe_specs = (qwen3_moe.qwen3_moe_param_specs(
        model_cfg, tp_axis="tp",
        ep_axis="ep" if ep > 1 else None,
        pp_axis="pp" if pp > 1 else None) if is_moe else None)
    if cfg.optimizer_name.lower() == "adafactor":
        from scaletorch_tpu.parallel.tensor_parallel import llama_param_specs

        tx, _ = create_optimizer(
            cfg, include_clip=False,
            param_specs=(moe_specs if is_moe else llama_param_specs(
                model_cfg, tp_axis="tp",
                pp_axis="pp" if pp > 1 else None)),
            axis_sizes=dict(mm.mesh.shape),
        )
    else:
        tx, _ = create_optimizer(cfg, include_clip=False)

    step_fn, p_specs, o_specs = make_spmd_train_step(
        mm, mod.forward, model_cfg, tx, params,
        attention_backend=resolve_attention_backend(
            cfg.attention_backend, context_parallel=cp > 1),
        gradient_checkpointing=gc,
        remat_policy=remat_policy,
        sequence_parallel=sp,
        max_grad_norm=cfg.max_grad_norm,
        param_specs=moe_specs,
        model_kwargs={"ep_axis": "ep" if ep > 1 else None} if is_moe else None,
        model_family="qwen3_moe" if is_moe else "llama",
        pp_schedule=cfg.pp_engine,
        pp_vpp=pp_vpp,
        cp_layout=cfg.cp_layout,
    )
    opt_state = jax.eval_shape(tx.init, params)
    rows = micro_bs * dp * ep
    batch = {
        "input_ids": jax.ShapeDtypeStruct(
            (grad_accum, rows, seq), jnp.int32),
        "target_ids": jax.ShapeDtypeStruct(
            (grad_accum, rows, seq), jnp.int32),
        "position_ids": jax.ShapeDtypeStruct((grad_accum, seq), jnp.int32),
    }
    return step_fn.lower(params, opt_state, batch)


def analyze(args_ns, *, gc: bool, remat_policy: str) -> dict:
    from scaletorch_tpu.analysis.memory import accounting_from_compiled

    lowered = build_lowered(
        args_ns.model, seq=args_ns.seq, micro_bs=args_ns.bs,
        grad_accum=args_ns.accum, gc=gc, remat_policy=remat_policy,
        gen=args_ns.gen, param_dtype=args_ns.param_dtype,
        optimizer=args_ns.optimizer,
        dp=args_ns.dp, tp=args_ns.tp, cp=args_ns.cp, pp=args_ns.pp,
        ep=args_ns.ep, sp=args_ns.sp, pp_engine=args_ns.pp_engine,
        pp_vpp=args_ns.pp_vpp, moe_dispatch=args_ns.moe_dispatch)
    # XLA:TPU enforces the HBM budget at compile time (RESOURCE_EXHAUSTED
    # on overflow), so a successful compile IS the fit verdict — the
    # caller's except path records the failure. The size fields below are
    # reported for composition analysis, not re-judged against a budget
    # (donated-argument aliasing makes any client-side sum double-count).
    # The argument/temp/alias/peak math is the SAME accounting the
    # jaxlint memory tier gates on (analysis/memory.py) — one
    # implementation, two consumers.
    compiled = lowered.compile()
    acct = accounting_from_compiled(compiled)
    if acct is None:
        raise RuntimeError(
            "compiled.memory_analysis() reported nothing for the AOT "
            "TPU target — libtpu too old for memory accounting?"
        )
    try:
        cost = compiled.cost_analysis() or {}
        flops = cost.get("flops")
    except Exception:  # noqa: BLE001 — cost analysis is best-effort
        flops = None
    return {
        **({"step_tflops": round(flops / 1e12, 2)} if flops else {}),
        "model": args_ns.model, "seq": args_ns.seq, "bs": args_ns.bs,
        "accum": args_ns.accum, "gc": gc, "remat_policy": remat_policy,
        "gen": args_ns.gen, "param_dtype": args_ns.param_dtype,
        **{ax: getattr(args_ns, ax) for ax in ("dp", "tp", "cp", "pp", "ep")
           if getattr(args_ns, ax) > 1},
        **({"sp": True} if args_ns.sp else {}),
        **({"pp_engine": args_ns.pp_engine} if args_ns.pp > 1 else {}),
        **({"moe_dispatch": args_ns.moe_dispatch}
           if args_ns.moe_dispatch != "auto" else {}),
        "argument_gb": round(acct.argument_bytes / 1e9, 3),
        "temp_gb": round(acct.temp_bytes / 1e9, 3),
        "output_gb": round(acct.output_bytes / 1e9, 3),
        "alias_gb": round(acct.alias_bytes / 1e9, 3),
        "code_mb": round(acct.generated_code_bytes / 1e6, 1),
        "upper_bound_gb": round(acct.peak_bytes / 1e9, 3),
        "fits_hbm": True,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="qwen3-0.6b")
    ap.add_argument("--seq", type=int, default=8192)
    ap.add_argument("--bs", type=int, default=1)
    ap.add_argument("--accum", type=int, default=1)
    ap.add_argument("--gc", action="store_true")
    ap.add_argument("--gen", default="v5e", choices=sorted(HBM_GB))
    ap.add_argument("--param-dtype", default="float32",
                    choices=["float32", "bfloat16"])
    ap.add_argument("--optimizer", default="adamw")
    for ax in ("dp", "tp", "cp", "pp", "ep"):
        ap.add_argument(f"--{ax}", type=int, default=1)
    ap.add_argument("--sp", action="store_true", help="sequence parallel")
    ap.add_argument("--pp-engine", default="afab",
                    choices=["afab", "memory_chunked", "1f1b", "interleaved"],
                    help="pipeline schedule to analyze (afab is the "
                         "config/train.py default; memory_chunked (alias 1f1b) is the O(pp)-memory "
                         "chunked schedule; interleaved is the virtual-stage "
                         "circular pipeline — pair with --pp-vpp)")
    ap.add_argument("--pp-vpp", type=int, default=1,
                    help="virtual stages per rank (pp_engine=interleaved); "
                         "the vpp x tick-carry memory shows up in temp_gb")
    ap.add_argument("--moe-dispatch", default="auto",
                    choices=["auto", "einsum", "index"],
                    help="capacity-dispatch token movement (MoE models)")
    ap.add_argument("--policies", nargs="*", default=None,
                    help="remat policies to compare (implies --gc)")
    ap.add_argument("--sweep-gc", action="store_true",
                    help="compare gc off vs on")
    args_ns = ap.parse_args()

    if os.environ.get(_CHILD_ENV) != "1":
        sys.exit(_reexec_clean(sys.argv[1:]))

    variants = []
    if args_ns.policies:
        variants = [(True, p) for p in args_ns.policies]
    elif args_ns.sweep_gc:
        variants = [(False, "nothing_saveable"), (True, "nothing_saveable")]
    else:
        variants = [(args_ns.gc, "nothing_saveable")]

    for gc, policy in variants:
        try:
            row = analyze(args_ns, gc=gc, remat_policy=policy)
        except Exception as e:  # noqa: BLE001 — per-variant isolation
            row = {"model": args_ns.model, "gc": gc, "remat_policy": policy,
                   "error": repr(e)[:300]}
            if "RESOURCE_EXHAUSTED" in row["error"]:
                row["fits_hbm"] = False
        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
