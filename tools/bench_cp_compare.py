#!/usr/bin/env python
"""Measure the context-parallel strategies against each other.

One command produces the ring-contiguous vs ring-zigzag vs Ulysses
step-time comparison at a given geometry (the measurement VERDICT r2 #4
asks for — it needs cp > 1, i.e. a real multi-chip pod; the single
driver chip cannot host a cp ring). On a CPU mesh the numbers attest
mechanics, not performance (serial device emulation hides the load
imbalance zigzag fixes).

    python tools/bench_cp_compare.py --cp 4 --dp 2 --seq 8192   # pod
    python tools/bench_cp_compare.py --cpu --seq 1024           # mechanics

Output: one JSON object with per-strategy step_time/tokens-per-second
and the zigzag:contiguous / ulysses:contiguous speedups.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="qwen3-0.6b")
    ap.add_argument("--cp", type=int, default=4)
    ap.add_argument("--dp", type=int, default=2)
    ap.add_argument("--seq", type=int, default=8192)
    ap.add_argument("--gc", action="store_true")
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--warmup", type=int, default=2)
    ap.add_argument("--cpu", action="store_true",
                    help="force a cp*dp virtual CPU mesh (mechanics only)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    if args.cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["PALLAS_AXON_POOL_IPS"] = ""
        os.environ["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count={args.cp * args.dp}"
        )
    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    from scaletorch_tpu.benchmark import benchmark_config, make_bench_args

    strategies = {
        "ring_contiguous": {"attention_backend": "ring",
                            "cp_layout": "contiguous"},
        "ring_zigzag": {"attention_backend": "ring", "cp_layout": "zigzag"},
        "ulysses": {"attention_backend": "ulysses"},
    }
    results = {}
    for name, extra in strategies.items():
        cfg = make_bench_args(
            args.model, seq=args.seq, cp=args.cp, dp=args.dp, gc=args.gc,
            dtype="float32" if args.cpu else "bfloat16", extra=extra,
        )
        try:
            r = benchmark_config(cfg, warmup=args.warmup, steps=args.steps)
            results[name] = {k: r[k] for k in
                             ("step_time_s", "tokens_per_second", "loss")}
        except Exception as e:  # noqa: BLE001 — e.g. ulysses kv-head cap
            results[name] = {"error": repr(e)[:200]}
        print(f"{name}: {results[name]}", flush=True)

    base = results.get("ring_contiguous", {}).get("step_time_s")
    out = {
        "geometry": {"model": args.model, "cp": args.cp, "dp": args.dp,
                     "seq": args.seq, "gc": args.gc,
                     "device": "cpu-mechanics" if args.cpu
                               else jax.devices()[0].device_kind},
        **results,
    }
    if base:
        for name in ("ring_zigzag", "ulysses"):
            st = results.get(name, {}).get("step_time_s")
            if st:
                out[f"{name}_speedup_vs_contiguous"] = round(base / st, 3)
    print(json.dumps(out, indent=1))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(out, f, indent=1)
    if all("error" in results[s] for s in strategies):
        sys.exit(1)  # a fully-failed run must not look like a measurement


if __name__ == "__main__":
    main()
