#!/usr/bin/env python
"""Decode microbenchmark: KV-cache engine vs the retired recompute loop.

Arms over tiny CPU-friendly models (>= 512 generated tokens for the
cached-vs-recompute pair — ISSUE 4 acceptance):

  * ``recompute``: the original cache-less sampler
    (models/gpt_moe.generate_recompute) — a full O(S_max² · L) forward
    per emitted token;
  * ``cached``: the KV-cached ``generate`` — one prefill, then
    O(S_max · L) per token against the cache;
  * ``engine``: the same generation through the continuous-batching
    InferenceEngine on a Llama config (prefill + per-step jitted decode
    with host-side slot bookkeeping — the serving-loop overhead arm);
  * ``paged vs dense`` (ISSUE 10): the paged-cache engine against the
    dense one on the same request schedule — tok/s, cache HBM bytes per
    layout (``kv_cache_bytes``), and the max admissible concurrency at
    EQUAL cache HBM: the dense layout admits ``B`` requests whatever
    their length; a pool of the same bytes admits
    ``capacity // pages_per_request`` — attested by actually admitting
    them into a paged engine, not just arithmetic;
  * ``disagg vs colocated`` (ISSUE 19): the disaggregated prefill/
    decode engine (inference/disagg.py, MPMD slices + page handoff)
    against the colocated paged engine on the same schedule — tok/s,
    per-slice busy fractions, handoff pages/bytes, and the relative
    overhead of the handoff seam. Needs >= 2 devices; on the phase-0
    CPU-fallback path the process self-provisions 8 virtual host
    devices before jax initializes. Exit 1 only on parity breakage;
    the < 15% overhead target is attested warn-only — at CPU-sim
    microbench sizes per-step dispatch and the synchronous handoff
    copy dominate and the row trips it freely; on real slices the
    handoff amortizes over the decode stream.

Startup runs the PR 5 phase-0 gate (bench.py): a dead relay tunnel or a
cpu-pinned JAX_PLATFORMS pins this process to the CPU backend BEFORE
jax initializes, so the bench can never wedge CI rediscovering a dead
TPU the way the r03-r05 rows did.

Writes JSON under results/ (gitignored) and prints a table.

Usage:
    JAX_PLATFORMS=cpu python tools/bench_decode.py [--tokens 512]
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def phase0_gate() -> str | None:
    """PR 5 phase-0 fallback decision, BEFORE any jax import: reuse
    bench.py's `_cpu_fallback_reason` (BENCH_FORCE_CPU override, dead-
    relay probe, cpu-pinned platform list) and, when it abstains, the
    bounded backend probe child. A non-None reason pins this process to
    the CPU backend with pallas disabled — the same env the bench
    orchestrator's CPU child runs under."""
    spec = importlib.util.spec_from_file_location(
        "_bench_gate", os.path.join(REPO, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    reason = bench._cpu_fallback_reason()
    already_cpu = "cpu" in os.environ.get("JAX_PLATFORMS", "").lower()
    if (reason is None and not already_cpu
            and os.environ.get("BENCH_FORCE_CPU", "") != "0"):
        reason = bench._probe_says_no_tpu()
    if reason is not None:
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["PALLAS_AXON_POOL_IPS"] = ""
        os.environ["SCALETORCH_TPU_DISABLE_PALLAS"] = "1"
        print(json.dumps({"event": "cpu_fallback", "reason": reason}),
              file=sys.stderr, flush=True)
    return reason


def _time_tokens(fn, n_tokens: int, repeats: int = 1):
    """(tokens/s, seconds) for fn() generating n_tokens, after a warmup
    call that eats compile time."""
    fn()  # warmup + compile
    t0 = time.perf_counter()
    for _ in range(repeats):
        fn()
    dt = (time.perf_counter() - t0) / repeats
    return n_tokens / dt, dt


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--tokens", type=int, default=512,
                    help="generated tokens per arm (>= 512 for the "
                         "acceptance run)")
    ap.add_argument("--prompt", type=int, default=8)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--embd", type=int, default=64)
    ap.add_argument("--repeats", type=int, default=1)
    ap.add_argument("--page_size", type=int, default=16,
                    help="paged-cache page size for the paged-vs-dense row")
    ap.add_argument("--out", default=os.path.join(REPO, "results",
                                                  "bench_decode.json"))
    args = ap.parse_args()

    fallback_reason = phase0_gate()

    # the disagg row needs >= 2 devices; on the CPU path (fallback or
    # an explicitly cpu-pinned platform list) split the host into 8
    # virtual devices BEFORE jax initializes — same knob the engine
    # tests and `serve.py --disagg` use
    if ("cpu" in os.environ.get("JAX_PLATFORMS", "").lower()
            and "--xla_force_host_platform_device_count"
            not in os.environ.get("XLA_FLAGS", "")):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8").strip()

    import jax
    import jax.numpy as jnp

    from scaletorch_tpu.models import gpt_moe, llama
    from scaletorch_tpu.inference import InferenceEngine, SamplingParams

    block = args.prompt + args.tokens
    cfg = gpt_moe.GPTMoEConfig(
        block_size=block, vocab_size=256, n_layer=args.layers, n_head=4,
        n_embd=args.embd, use_moe=False,
    )
    params = gpt_moe.init_params(jax.random.PRNGKey(0), cfg)
    prompt = (jnp.arange(args.prompt, dtype=jnp.int32) % 256)[None, :]

    def run_cached():
        out = gpt_moe.generate(params, prompt, cfg,
                               max_new_tokens=args.tokens, temperature=0.0)
        jax.block_until_ready(out)
        return out

    def run_recompute():
        out = gpt_moe.generate_recompute(
            params, prompt, cfg, max_new_tokens=args.tokens, temperature=0.0)
        jax.block_until_ready(out)
        return out

    print(f"GPT block={block} L={args.layers} d={args.embd}; "
          f"{args.tokens} tokens per arm")
    cached_tps, cached_s = _time_tokens(run_cached, args.tokens,
                                        args.repeats)
    print(f"  cached    : {cached_tps:10.1f} tok/s  ({cached_s:.2f}s)")
    recomp_tps, recomp_s = _time_tokens(run_recompute, args.tokens,
                                        args.repeats)
    print(f"  recompute : {recomp_tps:10.1f} tok/s  ({recomp_s:.2f}s)")

    # sanity: both arms emit the same greedy continuation
    same = bool(jnp.array_equal(run_cached(), run_recompute()))

    # engine arm: llama tiny through the continuous-batching loop
    lcfg = llama.LlamaConfig(
        vocab_size=256, hidden_size=args.embd, intermediate_size=2 * args.embd,
        num_hidden_layers=args.layers, num_attention_heads=4,
        num_key_value_heads=2, dtype=jnp.float32,
    )
    lparams = llama.init_params(jax.random.PRNGKey(1), lcfg)

    eng = InferenceEngine(
        lparams, lcfg, max_slots=1, max_seq=block,
        prefill_len=args.prompt,
        sampling=SamplingParams(temperature=0.0),
    )

    def run_engine():
        eng.submit(list(range(1, args.prompt + 1)),
                   max_new_tokens=args.tokens)
        eng.run()

    run_engine()  # warmup: compiles the engine's prefill + decode steps
    t0 = time.perf_counter()
    run_engine()
    engine_s = time.perf_counter() - t0
    engine_tps = args.tokens / engine_s
    print(f"  engine    : {engine_tps:10.1f} tok/s  ({engine_s:.2f}s)  "
          f"[decode compiles: {eng.decode_compile_count}]")

    speedup = cached_tps / recomp_tps
    print(f"\n  cached vs recompute speedup: {speedup:.2f}x  "
          f"(greedy outputs identical: {same})")

    # ---- paged vs dense row (ISSUE 10) ---------------------------------
    from scaletorch_tpu.inference.kv_cache import ceil_div, kv_cache_bytes

    ps = args.page_size
    dense_slots, s_max = 2, 256
    # 64-token requests, but keep at least one generated token so a big
    # --prompt can't degenerate the row into zero-token requests (which
    # would zero row_tokens and spuriously trip the >= 2x gate below)
    req_prompt = args.prompt
    req_new = max(64 - req_prompt, 1)
    schedule = [(list(range(1, req_prompt + 1)), req_new),
                ([5] * req_prompt, req_new)]

    def build(layout, **kw):
        return InferenceEngine(
            lparams, lcfg, max_slots=dense_slots, max_seq=s_max,
            prefill_len=req_prompt, cache_layout=layout,
            sampling=SamplingParams(temperature=0.0), **kw)

    def serve(e):
        ids = [e.submit(p, max_new_tokens=n) for p, n in schedule]
        res = e.run()
        return [res[i].tokens for i in ids]

    dense_eng = build("dense")
    out_dense = serve(dense_eng)  # warmup/compile
    t0 = time.perf_counter()
    out_dense = serve(dense_eng)
    dense_s = time.perf_counter() - t0
    paged_eng = build("paged", page_size=ps)
    out_paged = serve(paged_eng)
    t0 = time.perf_counter()
    out_paged = serve(paged_eng)
    paged_s = time.perf_counter() - t0
    row_tokens = sum(n for _, n in schedule)
    paged_same = out_dense == out_paged

    dense_bytes = kv_cache_bytes(lcfg, dense_slots, s_max, jnp.float32)
    page_bytes = kv_cache_bytes(lcfg, 1, ps, jnp.float32, layout="paged",
                                page_size=ps, num_pages=1)
    pool_pages = dense_bytes // page_bytes       # equal-HBM pool size
    pages_per_req = ceil_div(req_prompt + req_new, ps)
    admissible_paged = max((pool_pages - 1) // pages_per_req, 0)  # - TRASH
    if admissible_paged >= 1:
        # attest: a pool of exactly that many pages really admits them
        # all concurrently (page-budget admission, not slot arithmetic)
        attest = InferenceEngine(
            lparams, lcfg, max_slots=admissible_paged, max_seq=s_max,
            prefill_len=req_prompt, cache_layout="paged", page_size=ps,
            num_pages=pool_pages, prefix_cache=False,
            sampling=SamplingParams(temperature=0.0))
        for k in range(admissible_paged):
            attest.submit([k + 1] * req_prompt, max_new_tokens=req_new)
        attest.step()
        # everything admitted within the single step was resident at
        # once — counted at admission, not after it, so one-token
        # requests that retire inside the step still attest their
        # concurrency
        concurrent = attest.metrics.requests_admitted
    else:
        # degenerate sweep geometry (page_size ~ the whole dense cache):
        # an equal-HBM pool can't hold even one request, nothing to
        # attest — report 0 and let the warn-only gate handle the ratio
        concurrent = 0
    paged_pool_bytes = kv_cache_bytes(
        lcfg, dense_slots, s_max, jnp.float32, layout="paged",
        page_size=ps, num_pages=pool_pages)
    ratio = concurrent / dense_slots

    print(f"\n  paged vs dense (B={dense_slots}, S_max={s_max}, "
          f"page={ps}, req={req_prompt + req_new} tokens):")
    print(f"    dense : {row_tokens / dense_s:10.1f} tok/s  "
          f"cache {dense_bytes / 2**20:.2f} MiB  "
          f"max concurrent {dense_slots}")
    print(f"    paged : {row_tokens / paged_s:10.1f} tok/s  "
          f"pool  {paged_pool_bytes / 2**20:.2f} MiB  "
          f"max concurrent {concurrent} at equal HBM "
          f"({ratio:.1f}x, greedy identical: {paged_same})")

    # ---- disagg vs colocated row (ISSUE 19) ----------------------------
    disagg_row = None
    if len(jax.devices()) < 2:
        print(f"\n  disagg vs colocated: skipped (needs >= 2 devices, "
              f"have {len(jax.devices())})")
    else:
        from scaletorch_tpu.inference import DisaggregatedEngine

        dis_eng = DisaggregatedEngine(
            lparams, lcfg, max_slots=dense_slots, max_seq=s_max,
            prefill_len=req_prompt, page_size=ps,
            sampling=SamplingParams(temperature=0.0))
        serve(dis_eng)  # warmup: compiles both slice programs
        dis_eng.metrics.reset_window()
        t0 = time.perf_counter()
        out_disagg = serve(dis_eng)
        disagg_s = time.perf_counter() - t0
        p_busy, d_busy = dis_eng.metrics.busy_fractions()
        disagg_same = out_disagg == out_paged
        overhead_pct = (disagg_s - paged_s) / paged_s * 100.0
        try:
            dis_eng.check_conservation()  # raises on a page leak
            conservation_ok = True
        except AssertionError:
            conservation_ok = False
        n_p = dis_eng.metrics.prefill_slice_devices
        n_d = dis_eng.metrics.decode_slice_devices
        print(f"\n  disagg vs colocated (split {n_p}:{n_d}, "
              f"page={ps}, same schedule):")
        print(f"    colocated : {row_tokens / paged_s:10.1f} tok/s")
        print(f"    disagg    : {row_tokens / disagg_s:10.1f} tok/s  "
              f"overhead {overhead_pct:+.1f}%  "
              f"busy p={p_busy:.2f} d={d_busy:.2f}  "
              f"handoff {dis_eng.metrics.pages_handed_off} pages / "
              f"{dis_eng.metrics.handoff_bytes} B  "
              f"(greedy identical: {disagg_same}, compiles "
              f"{dis_eng.prefill_compile_count}/"
              f"{dis_eng.decode_compile_count}, conservation "
              f"{'ok' if conservation_ok else 'LEAK'})")
        disagg_row = {
            "slice_split": [n_p, n_d],
            "colocated_tokens_per_s": row_tokens / paged_s,
            "disagg_tokens_per_s": row_tokens / disagg_s,
            "overhead_pct": overhead_pct,
            "prefill_busy_fraction": p_busy,
            "decode_busy_fraction": d_busy,
            "pages_handed_off": dis_eng.metrics.pages_handed_off,
            "handoff_bytes": dis_eng.metrics.handoff_bytes,
            "greedy_outputs_identical": disagg_same,
            "conservation_ok": conservation_ok,
        }

    result = {
        "config": {"block_size": block, "layers": args.layers,
                   "embd": args.embd, "tokens": args.tokens,
                   "prompt": args.prompt},
        "cached_tokens_per_s": cached_tps,
        "recompute_tokens_per_s": recomp_tps,
        "engine_tokens_per_s": engine_tps,
        "speedup_cached_vs_recompute": speedup,
        "greedy_outputs_identical": same,
        "paged_vs_dense": {
            "page_size": ps,
            "request_tokens": req_prompt + req_new,
            "dense_tokens_per_s": row_tokens / dense_s,
            "paged_tokens_per_s": row_tokens / paged_s,
            "dense_cache_bytes": dense_bytes,
            "paged_pool_bytes_at_equal_hbm": paged_pool_bytes,
            "max_concurrent_dense": dense_slots,
            "max_concurrent_paged_at_equal_hbm": concurrent,
            "concurrency_ratio": ratio,
            "greedy_outputs_identical": paged_same,
        },
        "disagg_vs_colocated": disagg_row,
        "cpu_fallback_reason": fallback_reason,
        "backend": jax.default_backend(),
    }
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(result, f, indent=2)
    print(f"  wrote {args.out}")
    if speedup <= 1.0:
        print("  WARNING: cached decode did not beat recompute", file=sys.stderr)
        sys.exit(1)
    if not paged_same:
        print("  WARNING: paged greedy outputs diverged from dense",
              file=sys.stderr)
        sys.exit(1)
    if disagg_row is not None:
        if not disagg_row["greedy_outputs_identical"]:
            print("  WARNING: disagg greedy outputs diverged from "
                  "colocated", file=sys.stderr)
            sys.exit(1)
        if disagg_row["overhead_pct"] >= 15.0:
            # perf attestation is warn-only: CPU-sim timing jitter must
            # not flake CI; parity above is the hard gate
            print(f"  WARNING: disagg overhead "
                  f"{disagg_row['overhead_pct']:.1f}% >= 15% vs "
                  "colocated", file=sys.stderr)
    if ratio < 2.0:
        print(f"  WARNING: paged concurrency gain {ratio:.1f}x < 2x at "
              "equal HBM", file=sys.stderr)
        # the >= 2x acceptance gate is defined on the default request
        # geometry; exploratory --prompt/--page_size sweeps legitimately
        # land below it (e.g. page_size ~ request length) and only warn
        if (args.prompt == ap.get_default("prompt")
                and args.page_size == ap.get_default("page_size")):
            sys.exit(1)


if __name__ == "__main__":
    main()
