#!/usr/bin/env python
"""Decode microbenchmark: KV-cache engine vs the retired recompute loop.

Two arms over the same tiny GPT model (CPU-friendly sizes, >= 512
generated tokens — ISSUE 4 acceptance):

  * ``recompute``: the original cache-less sampler
    (models/gpt_moe.generate_recompute) — a full O(S_max² · L) forward
    per emitted token;
  * ``cached``: the KV-cached ``generate`` — one prefill, then
    O(S_max · L) per token against the cache;
  * ``engine``: the same generation through the continuous-batching
    InferenceEngine on a Llama config (prefill + per-step jitted decode
    with host-side slot bookkeeping — the serving-loop overhead arm).

Writes JSON under results/ (gitignored) and prints a table.

Usage:
    JAX_PLATFORMS=cpu python tools/bench_decode.py [--tokens 512]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _time_tokens(fn, n_tokens: int, repeats: int = 1):
    """(tokens/s, seconds) for fn() generating n_tokens, after a warmup
    call that eats compile time."""
    fn()  # warmup + compile
    t0 = time.perf_counter()
    for _ in range(repeats):
        fn()
    dt = (time.perf_counter() - t0) / repeats
    return n_tokens / dt, dt


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--tokens", type=int, default=512,
                    help="generated tokens per arm (>= 512 for the "
                         "acceptance run)")
    ap.add_argument("--prompt", type=int, default=8)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--embd", type=int, default=64)
    ap.add_argument("--repeats", type=int, default=1)
    ap.add_argument("--out", default=os.path.join(REPO, "results",
                                                  "bench_decode.json"))
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from scaletorch_tpu.models import gpt_moe, llama
    from scaletorch_tpu.inference import InferenceEngine, SamplingParams

    block = args.prompt + args.tokens
    cfg = gpt_moe.GPTMoEConfig(
        block_size=block, vocab_size=256, n_layer=args.layers, n_head=4,
        n_embd=args.embd, use_moe=False,
    )
    params = gpt_moe.init_params(jax.random.PRNGKey(0), cfg)
    prompt = (jnp.arange(args.prompt, dtype=jnp.int32) % 256)[None, :]

    def run_cached():
        out = gpt_moe.generate(params, prompt, cfg,
                               max_new_tokens=args.tokens, temperature=0.0)
        jax.block_until_ready(out)
        return out

    def run_recompute():
        out = gpt_moe.generate_recompute(
            params, prompt, cfg, max_new_tokens=args.tokens, temperature=0.0)
        jax.block_until_ready(out)
        return out

    print(f"GPT block={block} L={args.layers} d={args.embd}; "
          f"{args.tokens} tokens per arm")
    cached_tps, cached_s = _time_tokens(run_cached, args.tokens,
                                        args.repeats)
    print(f"  cached    : {cached_tps:10.1f} tok/s  ({cached_s:.2f}s)")
    recomp_tps, recomp_s = _time_tokens(run_recompute, args.tokens,
                                        args.repeats)
    print(f"  recompute : {recomp_tps:10.1f} tok/s  ({recomp_s:.2f}s)")

    # sanity: both arms emit the same greedy continuation
    same = bool(jnp.array_equal(run_cached(), run_recompute()))

    # engine arm: llama tiny through the continuous-batching loop
    lcfg = llama.LlamaConfig(
        vocab_size=256, hidden_size=args.embd, intermediate_size=2 * args.embd,
        num_hidden_layers=args.layers, num_attention_heads=4,
        num_key_value_heads=2, dtype=jnp.float32,
    )
    lparams = llama.init_params(jax.random.PRNGKey(1), lcfg)

    eng = InferenceEngine(
        lparams, lcfg, max_slots=1, max_seq=block,
        prefill_len=args.prompt,
        sampling=SamplingParams(temperature=0.0),
    )

    def run_engine():
        eng.submit(list(range(1, args.prompt + 1)),
                   max_new_tokens=args.tokens)
        eng.run()

    run_engine()  # warmup: compiles the engine's prefill + decode steps
    t0 = time.perf_counter()
    run_engine()
    engine_s = time.perf_counter() - t0
    engine_tps = args.tokens / engine_s
    print(f"  engine    : {engine_tps:10.1f} tok/s  ({engine_s:.2f}s)  "
          f"[decode compiles: {eng.decode_compile_count}]")

    speedup = cached_tps / recomp_tps
    print(f"\n  cached vs recompute speedup: {speedup:.2f}x  "
          f"(greedy outputs identical: {same})")

    result = {
        "config": {"block_size": block, "layers": args.layers,
                   "embd": args.embd, "tokens": args.tokens,
                   "prompt": args.prompt},
        "cached_tokens_per_s": cached_tps,
        "recompute_tokens_per_s": recomp_tps,
        "engine_tokens_per_s": engine_tps,
        "speedup_cached_vs_recompute": speedup,
        "greedy_outputs_identical": same,
        "backend": jax.default_backend(),
    }
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(result, f, indent=2)
    print(f"  wrote {args.out}")
    if speedup <= 1.0:
        print("  WARNING: cached decode did not beat recompute", file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
