#!/usr/bin/env python
"""Measure the MoE dispatch implementations against each other.

One command produces the einsum (GShard one-hot) vs index (scatter/
gather) step-time comparison for a MoE config on whatever device is
present. The AOT cost analysis already shows the one-hot einsums are
62% of step FLOPs at E=128/top-8 (AOT_30B_A3B.json, 2.65x compiled-FLOP
reduction); this is the matching WALL-CLOCK measurement for a real chip.
On a CPU mesh the numbers attest mechanics, not performance.

    python tools/bench_moe_dispatch.py --model moe-mid --seq 4096   # chip
    python tools/bench_moe_dispatch.py --cpu --seq 256              # mechanics

Output: one JSON object with per-mode step_time/tokens-per-second and
the index:einsum speedup.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="moe-mid",
                    help="MoE preset (moe-mid = v5e-sized 30B-A3B shape "
                         "family; moe-tiny for CPU mechanics)")
    ap.add_argument("--seq", type=int, default=4096)
    ap.add_argument("--bs", type=int, default=1)
    ap.add_argument("--ep", type=int, default=1)
    ap.add_argument("--dp", type=int, default=1)
    ap.add_argument("--gc", action="store_true")
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--warmup", type=int, default=2)
    ap.add_argument("--cpu", action="store_true",
                    help="force an ep*dp virtual CPU mesh (mechanics only)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    if args.cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["PALLAS_AXON_POOL_IPS"] = ""
        # APPEND to any operator-exported XLA_FLAGS (replacing only a
        # stale device-count flag) instead of clobbering them
        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+",
            "",
            os.environ.get("XLA_FLAGS", ""),
        )
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count="
            f"{max(args.ep * args.dp, 1)}"
        ).strip()
    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    from scaletorch_tpu.benchmark import benchmark_config, make_bench_args

    results = {}
    for mode in ("einsum", "index"):
        cfg = make_bench_args(
            args.model, seq=args.seq, micro_bs=args.bs, ep=args.ep,
            dp=args.dp, gc=args.gc,
            dtype="float32" if args.cpu else "bfloat16",
            extra={"moe_dispatch": mode},
        )
        try:
            r = benchmark_config(cfg, warmup=args.warmup, steps=args.steps)
            results[mode] = {k: r[k] for k in
                             ("step_time_s", "tokens_per_second", "loss")}
        except Exception as e:  # noqa: BLE001 — e.g. OOM at large shapes
            results[mode] = {"error": repr(e)[:200]}
        print(f"{mode}: {results[mode]}", flush=True)

    out = {
        "geometry": {"model": args.model, "seq": args.seq, "bs": args.bs,
                     "ep": args.ep, "dp": args.dp, "gc": args.gc,
                     "device": "cpu-mechanics" if args.cpu
                               else jax.devices()[0].device_kind},
        **results,
    }
    base = results.get("einsum", {}).get("step_time_s")
    st = results.get("index", {}).get("step_time_s")
    if base and st:
        out["index_speedup_vs_einsum"] = round(base / st, 3)
    print(json.dumps(out, indent=1))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(out, f, indent=1)
    if all("error" in results[m] for m in ("einsum", "index")):
        sys.exit(1)  # a fully-failed run must not look like a measurement


if __name__ == "__main__":
    main()
