#!/usr/bin/env python
"""Benchmark one configuration and print a JSON result.

Counterpart of reference tools/bench_single.py (one model/shape timed
with warmup + steady window). Thin CLI over scaletorch_tpu.benchmark.

Usage:
    python tools/bench_single.py --model qwen3-0.6b --seq 8192 --gc
    python tools/bench_single.py --model qwen3-30b-a3b --seq 4096 \
        --tp 4 --ep 2
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="qwen3-0.6b",
                    help="preset name (scaletorch_tpu/models/presets.py)")
    ap.add_argument("--seq", type=int, default=8192)
    ap.add_argument("--bs", type=int, default=1)
    ap.add_argument("--ga", type=int, default=1)
    ap.add_argument("--gc", action="store_true")
    ap.add_argument("--sp", action="store_true")
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--pp", type=int, default=1)
    ap.add_argument("--dp", type=int, default=1)
    ap.add_argument("--cp", type=int, default=1)
    ap.add_argument("--ep", type=int, default=1)
    ap.add_argument("--pp_engine", default="afab")
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--remat_policy", default="nothing_saveable")
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--warmup", type=int, default=3)
    args = ap.parse_args()

    from scaletorch_tpu.benchmark import benchmark_config, make_bench_args

    cfg = make_bench_args(
        args.model, seq=args.seq, micro_bs=args.bs, grad_accum=args.ga,
        gc=args.gc, sp=args.sp, tp=args.tp, pp=args.pp, dp=args.dp,
        cp=args.cp, ep=args.ep, pp_engine=args.pp_engine, dtype=args.dtype,
        remat_policy=args.remat_policy,
    )
    r = benchmark_config(cfg, warmup=args.warmup, steps=args.steps)
    r["config"] = {
        "model": args.model, "seq": args.seq, "bs": args.bs, "ga": args.ga,
        "gc": args.gc, "sp": args.sp, "tp": args.tp, "pp": args.pp,
        "dp": args.dp, "cp": args.cp, "ep": args.ep, "dtype": args.dtype,
    }
    print(json.dumps(r))


if __name__ == "__main__":
    main()
