#!/usr/bin/env python
"""Convert a training checkpoint between layer storage orders.

``pp_engine='interleaved'`` stores the stacked layer axis in rank-major
virtual-stage order (pipeline_parallel.interleave_stacked_params); a
checkpoint saved under one engine cannot resume under another —
Trainer.load_checkpoint refuses via the ``layer_storage`` metadata and
points here. This tool rewrites the checkpoint offline:

    python tools/convert_layer_storage.py \
        --ckpt ckpts --out ckpts_vpp2 --to interleaved --pp 2 --vpp 2
    python tools/convert_layer_storage.py \
        --ckpt ckpts_vpp2 --out ckpts_plain --to model_order

The permutation is applied to every stacked-layer leaf in BOTH params
and optimizer state (adam moments and adafactor factored stats keep the
layer axis leading, so the same row permutation applies). ``--to
model_order`` reads pp/vpp from the checkpoint's own metadata.
"""

from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _permute_layers_subtrees(tree, idx, num_layers):
    """Apply row permutation ``idx`` to every leaf under any dict key
    named 'layers' whose leading dim == num_layers. The optimizer state
    mirrors the params dict structure (mu/nu/factored stats), so the same
    walk covers it."""

    def walk(node, in_layers):
        if isinstance(node, dict):
            return {
                k: walk(v, in_layers or k == "layers")
                for k, v in node.items()
            }
        if isinstance(node, (list, tuple)):
            out = [walk(v, in_layers) for v in node]
            return type(node)(out)
        if in_layers and hasattr(node, "shape") and node.ndim >= 1:
            if node.shape[0] == num_layers:
                return node[idx]
            if node.shape[0] == 1:
                # adafactor stores (1,) placeholders and layer-REDUCED
                # row/col stats under the mirrored 'layers' subtree
                # (trainer/factored.py); both are invariant under a layer
                # permutation — pass through untouched.
                return node
            raise ValueError(
                f"stacked-layer leaf with leading dim {node.shape[0]} != "
                f"num_layers {num_layers}: cannot permute a non-uniform "
                "stack (interleaved storage requires uniform stacking)"
            )
        return node

    return walk(tree, False)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ckpt", required=True, help="source checkpoint dir")
    ap.add_argument("--out", required=True, help="destination dir (new)")
    ap.add_argument("--step", type=int, default=None,
                    help="step to convert (default: latest)")
    ap.add_argument("--to", required=True,
                    choices=["interleaved", "model_order"])
    ap.add_argument("--pp", type=int, default=None,
                    help="pp degree (required for --to interleaved)")
    ap.add_argument("--vpp", type=int, default=None,
                    help="virtual stages (required for --to interleaved)")
    args = ap.parse_args()

    import numpy as np
    import orbax.checkpoint as ocp

    from scaletorch_tpu.parallel.pipeline_parallel import (
        _interleaved_layer_order,
        validate_interleaved_divisibility,
    )

    src = ocp.CheckpointManager(os.path.abspath(args.ckpt))
    step = args.step if args.step is not None else src.latest_step()
    if step is None:
        raise SystemExit(f"no checkpoints in {args.ckpt}")
    restored = src.restore(
        step,
        args=ocp.args.Composite(
            params=ocp.args.StandardRestore(),
            opt_state=ocp.args.StandardRestore(),
            extra=ocp.args.JsonRestore(),
        ),
    )
    params, opt_state = restored["params"], restored["opt_state"]
    extra = dict(restored["extra"] or {})
    cur = extra.get("layer_storage", "model_order")

    import jax

    lead_dims = {
        leaf.shape[0]
        for leaf in jax.tree.leaves(params["layers"])
        if hasattr(leaf, "shape")
    }
    if len(lead_dims) != 1:
        raise SystemExit(
            f"non-uniform stacked-layer leading dims {sorted(lead_dims)}: "
            "interleaved conversion needs a uniform stack"
        )
    (num_layers,) = lead_dims

    if args.to == "interleaved":
        if cur != "model_order":
            raise SystemExit(f"checkpoint is already {cur!r}")
        if not args.pp or not args.vpp:
            raise SystemExit("--to interleaved requires --pp and --vpp")
        pp, vpp = args.pp, args.vpp
        validate_interleaved_divisibility(num_layers, pp, vpp)
        idx = np.asarray(_interleaved_layer_order(num_layers, pp, vpp))
        new_storage = f"interleaved_pp{pp}_vpp{vpp}"
    else:
        if not cur.startswith("interleaved_pp"):
            raise SystemExit(
                f"checkpoint layer_storage is {cur!r}; nothing to invert")
        body = cur[len("interleaved_pp"):]
        pp, vpp = (int(x) for x in body.split("_vpp"))
        # metadata could be hand-edited/mismatched: an L that pp*vpp does
        # not divide would silently TRUNCATE the permutation below
        validate_interleaved_divisibility(num_layers, pp, vpp)
        idx = np.argsort(_interleaved_layer_order(num_layers, pp, vpp))
        new_storage = "model_order"

    params = _permute_layers_subtrees(params, idx, num_layers)
    opt_state = _permute_layers_subtrees(opt_state, idx, num_layers)
    extra["layer_storage"] = new_storage

    dst = ocp.CheckpointManager(os.path.abspath(args.out))
    dst.save(step, args=ocp.args.Composite(
        params=ocp.args.StandardSave(params),
        opt_state=ocp.args.StandardSave(opt_state),
        extra=ocp.args.JsonSave(extra),
    ))
    dst.wait_until_finished()
    print(f"step {step}: {cur} -> {new_storage} "
          f"(L={num_layers}, pp={pp}, vpp={vpp}) written to {args.out}")


if __name__ == "__main__":
    main()
