#!/usr/bin/env python
"""Search training knobs for the best MFU on the current chip.

Counterpart of reference tools/optimize_mfu.py (tries gc/compile/batch
variants and reports the winner). The TPU knobs that matter here:
remat policy (what GC saves), gradient checkpointing on/off, and
micro-batch size. Each variant runs in-process with warmup; OOM variants
are recorded and skipped.

Usage:
    python tools/optimize_mfu.py --model qwen3-0.6b --seq 8192
    python tools/optimize_mfu.py --policies nothing_saveable dots_saveable
"""

from __future__ import annotations

import argparse
import gc as _gc
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

_OOM = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory")


def _detect_gen(explicit: str | None) -> str:
    """Chip generation for the prefilter's HBM budget — the fit verdict
    must be judged against the chip the sweep will RUN on (dots_saveable
    at seq 16384 overflows a 16GB v5e but fits a 32GB v6e)."""
    if explicit:
        return explicit
    try:
        import jax

        kind = jax.local_devices()[0].device_kind.lower()
    except Exception:  # noqa: BLE001 — no device: default budget
        return "v5e"
    if "v6" in kind:
        return "v6e"
    if "v5" in kind and "lite" in kind:
        return "v5e"
    if "v5" in kind:
        return "v5p"
    if "v4" in kind:
        return "v4"
    return "v5e"


def _aot_prefilter(args, variants):
    """Compile-time HBM verdict per variant via tools/aot_memory.py (its
    own scrubbed-env subprocess — works with or without a chip). One
    subprocess per (micro_bs, gc) group: aot_memory takes every remat
    policy in a single invocation, so the JAX-import/lowering startup is
    paid per group, not per variant. Returns (kept_variants,
    dropped_labels); inconclusive compiles fail OPEN (kept) so an AOT
    infra problem never eats a real measurement."""
    gen = _detect_gen(args.aot_gen)

    def _run_knobs(shape):
        """The non-policy knobs that change the compiled memory picture.
        Pulled from the variant shape (extra{} carries config-level keys)
        so the prefilter compiles EXACTLY what the sweep will run — a
        future sweep knob (accum, optimizer, master-param dtype) must not
        silently diverge the fit verdict (ADVICE r4)."""
        extra = shape.get("extra") or {}
        return (
            shape.get("micro_bs", 1),
            bool(shape.get("gc")),
            shape.get("grad_accum", 1),
            extra.get("optimizer_name", "adamw"),
            extra.get("param_dtype", "float32"),
        )

    groups: dict = {}
    for label, shape in variants:
        groups.setdefault(_run_knobs(shape), []).append((label, shape))

    kept, dropped = [], []
    for (bs, gc, accum, optimizer, param_dtype), members in groups.items():
        cmd = [sys.executable, os.path.join(REPO, "tools", "aot_memory.py"),
               "--model", args.model, "--seq", str(args.seq),
               "--bs", str(bs), "--gen", gen,
               "--accum", str(accum), "--optimizer", optimizer,
               "--param-dtype", param_dtype]
        if gc:
            policies = []
            for _, shape in members:
                p = shape.get("remat_policy", "nothing_saveable")
                if p not in policies:
                    policies.append(p)
            cmd += ["--gc", "--policies", *policies]
        fit_by_policy: dict = {}
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=2400, cwd=REPO)
            for line in proc.stdout.splitlines():
                line = line.strip()
                if not line.startswith("{"):
                    continue
                row = json.loads(line)
                fit_by_policy[row.get("remat_policy")] = row.get(
                    "fits_hbm", True)
        except Exception as e:  # noqa: BLE001 — prefilter is best-effort
            print(f"aot-prefilter inconclusive for bs={bs} gc={gc} "
                  f"({repr(e)[:80]}); keeping its variants", flush=True)
        for label, shape in members:
            pol = shape.get("remat_policy", "nothing_saveable")
            if fit_by_policy.get(pol, True):
                kept.append((label, shape))
            else:
                dropped.append(label)
    # preserve the caller's sweep order
    order = {label: i for i, (label, _) in enumerate(variants)}
    kept.sort(key=lambda kv: order[kv[0]])
    return kept, dropped


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="qwen3-0.6b")
    ap.add_argument("--seq", type=int, default=8192)
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--warmup", type=int, default=2)
    ap.add_argument("--policies", nargs="*", default=[
        "nothing_saveable", "dots_saveable", "save_attn",
    ])
    ap.add_argument("--batch_sizes", nargs="*", type=int, default=[1, 2])
    ap.add_argument("--try_no_gc", action="store_true",
                    help="also try gradient_checkpointing off")
    ap.add_argument("--out", default=None, help="write results JSON here")
    ap.add_argument("--aot-prefilter", action="store_true",
                    help="AOT-compile each variant first (local libtpu, no "
                         "chip) and drop the ones XLA says cannot fit HBM — "
                         "no chip time is burned on known-OOM rows (e.g. "
                         "dots_saveable at seq 16384, AOT_SEQ16K.json)")
    ap.add_argument("--aot-gen", default=None,
                    choices=["v5e", "v6e", "v5p", "v4"],
                    help="chip generation for the prefilter's HBM budget; "
                         "default: detect from the attached device")
    ap.add_argument("--flash-blocks", nargs="*", default=None,
                    metavar="BQxBKV",
                    help="also sweep flash tile sizes on the best "
                         "gc/batch point, e.g. 256x512 512x512 512x1024 "
                         "(sets SCALETORCH_TPU_FLASH_BLOCK_Q/KV per run)")
    args = ap.parse_args()

    # Validate BEFORE the expensive sweeps: a typo'd spec must not crash
    # the run after minutes of completed benchmarks.
    flash_blocks = []
    for spec in args.flash_blocks or []:
        try:
            bq, bkv = (int(x) for x in spec.lower().split("x"))
        except ValueError:
            raise SystemExit(
                f"--flash-blocks entry {spec!r} is not BQxBKV (e.g. 512x512)"
            )
        flash_blocks.append((bq, bkv))

    from scaletorch_tpu.benchmark import benchmark_config, make_bench_args

    variants = []
    if args.try_no_gc:
        for bs in args.batch_sizes:
            variants.append((f"no-gc_bs{bs}", dict(gc=False, micro_bs=bs)))
    for policy in args.policies:
        for bs in args.batch_sizes:
            variants.append((
                f"gc-{policy}_bs{bs}",
                dict(gc=True, remat_policy=policy, micro_bs=bs),
            ))

    results = []
    if args.aot_prefilter:
        variants, dropped = _aot_prefilter(args, variants)
        for label in dropped:
            results.append({"label": label, "error": "AOT_NO_FIT"})
            print(f"{label:<28} AOT_NO_FIT (skipped — compile-time OOM)",
                  flush=True)

    for label, shape in variants:
        cfg = make_bench_args(args.model, seq=args.seq, **shape)
        try:
            r = benchmark_config(cfg, warmup=args.warmup, steps=args.steps)
            results.append({"label": label, **r})
            print(f"{label:<28} MFU {r['mfu']:6.2f}%  "
                  f"tok/s {r['tokens_per_second']:>10,.0f}", flush=True)
        except Exception as e:  # noqa: BLE001 — record and continue
            status = "OOM" if any(m in repr(e) for m in _OOM) else "FAILED"
            results.append({"label": label, "error": status})
            print(f"{label:<28} {status}", flush=True)
            _gc.collect()

    ok = [r for r in results if "mfu" in r]
    if ok and flash_blocks:
        # Tile-size sweep on the winning shape: the kernel reads the env
        # registry at trace time, so each variant re-jits with its tiles.
        best_label = max(ok, key=lambda r: r["mfu"])["label"]
        best_shape = next(v for label, v in variants if label == best_label)
        for bq, bkv in flash_blocks:
            os.environ["SCALETORCH_TPU_FLASH_BLOCK_Q"] = str(bq)
            os.environ["SCALETORCH_TPU_FLASH_BLOCK_KV"] = str(bkv)
            label = f"flash_{bq}x{bkv}"
            try:
                cfg = make_bench_args(args.model, seq=args.seq, **best_shape)
                r = benchmark_config(cfg, warmup=args.warmup, steps=args.steps)
                results.append({"label": label, **r})
                print(f"{label:<28} MFU {r['mfu']:6.2f}%  "
                      f"tok/s {r['tokens_per_second']:>10,.0f}", flush=True)
            except Exception as e:  # noqa: BLE001
                status = "OOM" if any(m in repr(e) for m in _OOM) else "FAILED"
                results.append({"label": label, "error": status})
                print(f"{label:<28} {status}", flush=True)
                _gc.collect()
        for v in ("SCALETORCH_TPU_FLASH_BLOCK_Q", "SCALETORCH_TPU_FLASH_BLOCK_KV"):
            os.environ.pop(v, None)
        ok = [r for r in results if "mfu" in r]
    if ok:
        best = max(ok, key=lambda r: r["mfu"])
        print(f"\nbest: {best['label']} at {best['mfu']}% MFU "
              f"({best['tokens_per_second']:,.0f} tok/s)")
    if args.out:
        with open(args.out, "w") as f:
            json.dump(results, f, indent=1)
        print(f"results written to {args.out}")


if __name__ == "__main__":
    main()
