#!/usr/bin/env python
"""Search training knobs for the best MFU on the current chip.

Counterpart of reference tools/optimize_mfu.py (tries gc/compile/batch
variants and reports the winner). The TPU knobs that matter here:
remat policy (what GC saves), gradient checkpointing on/off, and
micro-batch size. Each variant runs in-process with warmup; OOM variants
are recorded and skipped.

Usage:
    python tools/optimize_mfu.py --model qwen3-0.6b --seq 8192
    python tools/optimize_mfu.py --policies nothing_saveable dots_saveable
"""

from __future__ import annotations

import argparse
import gc as _gc
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

_OOM = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="qwen3-0.6b")
    ap.add_argument("--seq", type=int, default=8192)
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--warmup", type=int, default=2)
    ap.add_argument("--policies", nargs="*", default=[
        "nothing_saveable", "dots_saveable", "save_attn",
    ])
    ap.add_argument("--batch_sizes", nargs="*", type=int, default=[1, 2])
    ap.add_argument("--try_no_gc", action="store_true",
                    help="also try gradient_checkpointing off")
    ap.add_argument("--out", default=None, help="write results JSON here")
    ap.add_argument("--flash-blocks", nargs="*", default=None,
                    metavar="BQxBKV",
                    help="also sweep flash tile sizes on the best "
                         "gc/batch point, e.g. 256x512 512x512 512x1024 "
                         "(sets SCALETORCH_TPU_FLASH_BLOCK_Q/KV per run)")
    args = ap.parse_args()

    # Validate BEFORE the expensive sweeps: a typo'd spec must not crash
    # the run after minutes of completed benchmarks.
    flash_blocks = []
    for spec in args.flash_blocks or []:
        try:
            bq, bkv = (int(x) for x in spec.lower().split("x"))
        except ValueError:
            raise SystemExit(
                f"--flash-blocks entry {spec!r} is not BQxBKV (e.g. 512x512)"
            )
        flash_blocks.append((bq, bkv))

    from scaletorch_tpu.benchmark import benchmark_config, make_bench_args

    variants = []
    if args.try_no_gc:
        for bs in args.batch_sizes:
            variants.append((f"no-gc_bs{bs}", dict(gc=False, micro_bs=bs)))
    for policy in args.policies:
        for bs in args.batch_sizes:
            variants.append((
                f"gc-{policy}_bs{bs}",
                dict(gc=True, remat_policy=policy, micro_bs=bs),
            ))

    results = []
    for label, shape in variants:
        cfg = make_bench_args(args.model, seq=args.seq, **shape)
        try:
            r = benchmark_config(cfg, warmup=args.warmup, steps=args.steps)
            results.append({"label": label, **r})
            print(f"{label:<28} MFU {r['mfu']:6.2f}%  "
                  f"tok/s {r['tokens_per_second']:>10,.0f}", flush=True)
        except Exception as e:  # noqa: BLE001 — record and continue
            status = "OOM" if any(m in repr(e) for m in _OOM) else "FAILED"
            results.append({"label": label, "error": status})
            print(f"{label:<28} {status}", flush=True)
            _gc.collect()

    ok = [r for r in results if "mfu" in r]
    if ok and flash_blocks:
        # Tile-size sweep on the winning shape: the kernel reads the env
        # registry at trace time, so each variant re-jits with its tiles.
        best_label = max(ok, key=lambda r: r["mfu"])["label"]
        best_shape = next(v for label, v in variants if label == best_label)
        for bq, bkv in flash_blocks:
            os.environ["SCALETORCH_TPU_FLASH_BLOCK_Q"] = str(bq)
            os.environ["SCALETORCH_TPU_FLASH_BLOCK_KV"] = str(bkv)
            label = f"flash_{bq}x{bkv}"
            try:
                cfg = make_bench_args(args.model, seq=args.seq, **best_shape)
                r = benchmark_config(cfg, warmup=args.warmup, steps=args.steps)
                results.append({"label": label, **r})
                print(f"{label:<28} MFU {r['mfu']:6.2f}%  "
                      f"tok/s {r['tokens_per_second']:>10,.0f}", flush=True)
            except Exception as e:  # noqa: BLE001
                status = "OOM" if any(m in repr(e) for m in _OOM) else "FAILED"
                results.append({"label": label, "error": status})
                print(f"{label:<28} {status}", flush=True)
                _gc.collect()
        for v in ("SCALETORCH_TPU_FLASH_BLOCK_Q", "SCALETORCH_TPU_FLASH_BLOCK_KV"):
            os.environ.pop(v, None)
        ok = [r for r in results if "mfu" in r]
    if ok:
        best = max(ok, key=lambda r: r["mfu"])
        print(f"\nbest: {best['label']} at {best['mfu']}% MFU "
              f"({best['tokens_per_second']:,.0f} tok/s)")
    if args.out:
        with open(args.out, "w") as f:
            json.dump(results, f, indent=1)
        print(f"results written to {args.out}")


if __name__ == "__main__":
    main()
