#!/usr/bin/env python
"""Measure the pipeline schedules against each other — honest accounting.

VERDICT r1 weak #3 asked for measured (not asserted) schedule numbers.
Background: the reference implements MPMD AFAB and 1F1B
(pipeline_parallel.py:457-671) where 1F1B interleaves F/B ticks to cut
the bubble AND bound memory. In this SPMD collective-permute design the
accounting differs:

  afab  : one fwd pipeline (M + pp - 1 ticks) + its autodiff mirror
          => bubble fraction (pp-1)/(M+pp-1), the SAME as textbook 1F1B,
          because idle SPMD stages burn their tick either way — manual
          F/B interleaving would cost M + 2(pp-1) combined ticks, i.e.
          strictly more. Boundary-activation memory is O(M).
  memory_chunked (reference-compat alias: 1f1b) : chunked accumulation in groups of pp microbatches
          => 1F1B's O(pp) boundary memory, at bubble fraction
          (pp-1)/(2*pp-1) per chunk.
  interleaved (vpp virtual stages per rank, circular ring)
          => M*vpp + pp - 1 ticks of 1/(pp*vpp)-stack chunks: bubble
          fraction (pp-1)/(M*vpp+pp-1) — afab's cut ~vpp x; predicted
          step time (M*vpp+pp-1)/(vpp*(M+pp-1)) of afab's. Costs vpp x
          boundary-carry memory and p2p volume
          (pipeline_parallel.interleaved_tick_schedule).

This tool measures steady-state step time for all three at a given
geometry (default pp=4, accum=8 on the virtual CPU mesh) and prints the
measured ratios next to the predicted tick ratios. Prediction for pp=4,
M=8: afab 11 fwd + 11 bwd ticks vs chunked 2x(7 + 7) = 28 -> ~1.27x
slower; interleaved vpp=2: 19 chunk-ticks vs afab 11 stage-ticks ->
19/22 = ~0.86x (13.6% faster). The model runs pp*vpp layers so every
engine shares the exact same network.

Usage (any host; forces the virtual CPU mesh unless --native):
    python tools/pp_schedule_compare.py [--pp 4] [--accum 8] [--vpp 2]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--pp", type=int, default=4)
    ap.add_argument("--dp", type=int, default=2)
    ap.add_argument("--accum", type=int, default=8)
    ap.add_argument("--vpp", type=int, default=2,
                    help="virtual stages per rank for the interleaved row")
    ap.add_argument("--seq", type=int, default=512)
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--warmup", type=int, default=2)
    ap.add_argument("--model", default="dense-tiny")
    ap.add_argument("--native", action="store_true",
                    help="use whatever devices jax sees (default: force a "
                         "pp*dp virtual CPU mesh)")
    ap.add_argument("--out", default=None,
                    help="also write the result JSON to this path "
                         "(committed evidence artifact)")
    args = ap.parse_args()

    if not args.native:
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["PALLAS_AXON_POOL_IPS"] = ""
        os.environ["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count={args.pp * args.dp}"
        )
    import jax

    if not args.native:
        jax.config.update("jax_platforms", "cpu")

    from scaletorch_tpu.benchmark import benchmark_config, make_bench_args
    from scaletorch_tpu.parallel.pipeline_parallel import (
        interleaved_tick_schedule,
    )

    # every engine runs the SAME network: pp*vpp layers (the interleaved
    # divisibility requirement, satisfied trivially by the others)
    n_layers = args.pp * args.vpp
    results = {}
    for engine in ("afab", "memory_chunked", "interleaved"):
        extra = {"num_hidden_layers": n_layers}
        if engine == "interleaved":
            extra["pp_virtual_stages"] = args.vpp
        cfg = make_bench_args(
            args.model, seq=args.seq, pp=args.pp, dp=args.dp,
            grad_accum=args.accum, pp_engine=engine, dtype="float32",
            extra=extra,
        )
        r = benchmark_config(cfg, warmup=args.warmup, steps=args.steps)
        results[engine] = r
        print(f"{engine}: step_time={r['step_time_s']}s "
              f"tok/s={r['tokens_per_second']}", flush=True)

    m, pp, vpp = args.accum, args.pp, args.vpp
    iacct = interleaved_tick_schedule(m, pp, vpp)
    pred = {
        "afab_ticks": 2 * (m + pp - 1),
        "afab_bubble": (pp - 1) / (m + pp - 1),
        "chunked_ticks": (m // pp) * 2 * (2 * pp - 1),
        "chunked_bubble": (pp - 1) / (2 * pp - 1),
        "interleaved_ticks": 2 * iacct["ticks"],
        "interleaved_bubble": iacct["bubble_fraction"],
    }
    measured_ratio = (
        results["memory_chunked"]["step_time_s"] / results["afab"]["step_time_s"]
    )
    predicted_ratio = pred["chunked_ticks"] / pred["afab_ticks"]
    measured_inter = (
        results["interleaved"]["step_time_s"] / results["afab"]["step_time_s"]
    )
    out = {
        "geometry": {"pp": pp, "dp": args.dp, "accum": m, "seq": args.seq,
                     "vpp": vpp, "num_hidden_layers": n_layers},
        "afab": results["afab"],
        "memory_chunked": results["memory_chunked"],
        "interleaved": results["interleaved"],
        "predicted": pred,
        "measured_slowdown_chunked_vs_afab": round(measured_ratio, 3),
        "predicted_slowdown_chunked_vs_afab": round(predicted_ratio, 3),
        "measured_interleaved_vs_afab": round(measured_inter, 3),
        "predicted_interleaved_vs_afab": round(
            iacct["relative_step_time"], 3),
        "recommendation": (
            "interleaved when num_hidden_layers % (pp*vpp) == 0 and the "
            "vpp x boundary-carry memory fits (bubble cut ~vpp x); afab "
            "otherwise; memory_chunked only when O(accum) boundary carries "
            "do not fit"
        ),
    }
    print(json.dumps(out, indent=1))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(out, f, indent=1)


if __name__ == "__main__":
    main()
