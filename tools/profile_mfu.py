#!/usr/bin/env python
"""Profile actual MFU with an analytic FLOPs breakdown + optional XLA trace.

Counterpart of reference tools/profile_mfu.py: print the per-component
FLOPs/token budget (linear / attention / embed+head), measure the real
train step with and without gradient checkpointing, and report achieved
TFLOP/s + MFU against the chip's peak. ``--trace DIR`` additionally
captures a ``jax.profiler`` trace of the steady-state steps for
tensorboard/xprof (the per-op timeline the reference gets from
torch_npu profiling).

Usage:
    python tools/profile_mfu.py --model qwen3-0.6b --seq 8192
    python tools/profile_mfu.py --model qwen3-0.6b --trace /tmp/xprof
"""

from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def flops_breakdown(p, seq: int) -> dict:
    """FLOPs/token by component (reference profile_mfu.py:60-82)."""
    h, l_ = p["hidden_size"], p["num_hidden_layers"]
    heads = p["num_attention_heads"]
    kv = p.get("num_key_value_heads", heads)
    hd = p.get("head_dim") or h // heads
    inter = p["intermediate_size"]
    v = p["vocab_size"]
    linear = 2 * l_ * (
        h * heads * hd + 2 * h * kv * hd + heads * hd * h + 3 * h * inter
    )
    attn = 2 * 2 * heads * hd * seq * l_
    embed = 2 * 2 * v * h
    fwd = linear + attn + embed
    return {
        "linear": linear, "attention": attn, "embed_head": embed,
        "forward": fwd, "train_3x": 3 * fwd,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="qwen3-0.6b")
    ap.add_argument("--seq", type=int, default=8192)
    ap.add_argument("--bs", type=int, default=1)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--trace", default=None,
                    help="write a jax.profiler trace of the timed steps here")
    ap.add_argument("--skip_no_gc", action="store_true",
                    help="only measure the GC variant (small-HBM chips)")
    args = ap.parse_args()

    from scaletorch_tpu.benchmark import benchmark_config, make_bench_args
    from scaletorch_tpu.models.presets import preset
    from scaletorch_tpu.utils.device import get_device_kind, get_theoretical_flops

    p = preset(args.model)
    br = flops_breakdown(p, args.seq)
    print(f"model={args.model} seq={args.seq} bs={args.bs}")
    print("FLOPs/token breakdown:")
    for k in ("linear", "attention", "embed_head", "forward", "train_3x"):
        print(f"  {k:<10} {br[k] / 1e9:8.2f} GFLOPs")
    peak = get_theoretical_flops()
    print(f"device: {get_device_kind()}  peak bf16 {peak / 1e12:.0f} TFLOP/s")

    variants = [("gc", True)] if args.skip_no_gc else [
        ("no-gc", False), ("gc", True),
    ]
    for label, gc in variants:
        cfg = make_bench_args(args.model, seq=args.seq, micro_bs=args.bs, gc=gc)
        try:
            if args.trace and gc:
                import jax

                os.makedirs(args.trace, exist_ok=True)
                with jax.profiler.trace(args.trace):
                    r = benchmark_config(cfg, warmup=args.warmup,
                                         steps=args.steps)
                print(f"trace written to {args.trace}")
            else:
                r = benchmark_config(cfg, warmup=args.warmup, steps=args.steps)
        except Exception as e:  # noqa: BLE001 — report, continue variants
            print(f"[{label}] FAILED: {repr(e)[:200]}")
            continue
        achieved = r["tokens_per_second"] * br["train_3x"] / 1e12
        print(f"[{label}] step {r['step_time_s'] * 1e3:.1f}ms | "
              f"tok/s {r['tokens_per_second']:,.0f} | "
              f"achieved {achieved:.1f} TFLOP/s | MFU {r['mfu']:.1f}%"
              + (f" | mem {r['memory_gb']}GB" if r["memory_gb"] else ""))


if __name__ == "__main__":
    main()
