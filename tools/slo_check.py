#!/usr/bin/env python
"""Grade serving telemetry against the checked-in SLOs (the CI gate).

Reads any mix of the gateway's observability artifacts and evaluates
them against one preset from tools/slo.json
(scaletorch_tpu/serving/slo.py grammar):

  * telemetry JSONL streams (positional args) — per-request ``access``
    records are the primary source (exact latency samples + outcome
    counts); ``latency_histograms`` records are merged (the histogram
    primitive's merge contract, exercised for real here) and used for
    any metric without exact samples; the last ``gateway_metrics``
    record supplies outcome counts when no access records exist;
  * ``--prom metrics.txt`` — a scraped ``/metrics`` exposition:
    ``scaletorch_request_<metric>_seconds_bucket`` histogram series are
    reconstructed (summed over tenant labels) and
    ``scaletorch_http_<outcome>`` counters supply outcomes. This is the
    acceptance path "the histogram series /metrics exposes are series
    slo_check accepts".

Usage:
    python tools/slo_check.py --slo tools/slo.json --preset tiny \\
        telemetry/gateway_events.jsonl [more.jsonl] [--prom metrics.txt]

Exit codes: 0 = within SLO, 1 = violation, 2 = usage error (missing or
malformed inputs). Runs on a jax-free interpreter — everything it
imports is pure stdlib.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from scaletorch_tpu.serving.slo import (  # noqa: E402
    LATENCY_OUTCOMES,
    evaluate_slo,
    format_report,
    load_slo,
    preset_targets,
)
from scaletorch_tpu.telemetry.histogram import LogHistogram  # noqa: E402

# The gateway's histogram metric names and their access-record fields.
METRIC_FIELDS = {
    "ttft": "ttft_s",
    "queue_wait": "queue_wait_s",
    "prefill": "prefill_s",
    "e2e": "e2e_s",
    # tpot has no per-request scalar (it is per-token); histogram /
    # prometheus sources cover it
}

# PR 7 terminal-outcome taxonomy (hardcoded: this tool must not import
# the jax-backed inference package).
OUTCOMES = ("ok", "shed", "timeout", "rejected", "quarantined", "aborted")

# the label block is matched GREEDILY up to the last '}' before the
# value: '}' is a legal character inside a quoted Prometheus label
# value (only \, " and newline are escaped), and tenant names are
# untrusted client strings — [^}]* would silently drop every series of
# a tenant named e.g. 'a}b' from the SLO evaluation
_PROM_LINE_RE = re.compile(r"^([A-Za-z0-9_:]+)(?:\{(.*)\})?\s+(\S+)$")
_PROM_LABEL_RE = re.compile(r'([A-Za-z0-9_]+)="((?:[^"\\]|\\.)*)"')
_PROM_BUCKET_RE = re.compile(
    r"^scaletorch_request_([a-z0-9_]+)_seconds_bucket$")


class PromHistogram:
    """A histogram reconstructed from ``_bucket`` exposition lines:
    (le, cumulative-count) pairs summed over label sets."""

    def __init__(self) -> None:
        self._by_le: Dict[float, int] = {}

    def add(self, le: float, count: int) -> None:
        self._by_le[le] = self._by_le.get(le, 0) + count

    def quantile(self, q: float) -> Optional[float]:
        if not self._by_le:
            return None
        pairs = sorted(self._by_le.items())
        total = pairs[-1][1]  # +Inf bucket is the largest le
        if total <= 0:
            return None
        rank = max(1, math.ceil(q * total))
        prev_le, prev_cum = 0.0, 0
        for le, cum in pairs:
            if cum >= rank:
                if math.isinf(le):
                    return prev_le  # best bound available
                frac = (rank - prev_cum) / max(1, cum - prev_cum)
                return prev_le + frac * (le - prev_le)
            prev_le, prev_cum = le, cum
        return pairs[-1][0]


def read_jsonl(path: str) -> List[dict]:
    out = []
    with open(path) as f:
        for n, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError as exc:
                raise ValueError(f"{path}:{n}: bad JSONL line: {exc}")
    return out


def parse_prom_text(text: str) -> Tuple[Dict[str, PromHistogram],
                                        Dict[str, int]]:
    """(histograms by metric, outcome counts) from a /metrics scrape."""
    hists: Dict[str, PromHistogram] = {}
    outcomes: Dict[str, int] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        match = _PROM_LINE_RE.match(line)
        if match is None:
            continue
        name, raw_labels, raw_value = match.groups()
        try:
            value = float(raw_value)
        except ValueError:
            continue
        bucket = _PROM_BUCKET_RE.match(name)
        if bucket is not None:
            labels = dict(_PROM_LABEL_RE.findall(raw_labels or ""))
            le_text = labels.get("le", "")
            le = float("inf") if le_text == "+Inf" else float(le_text)
            hists.setdefault(bucket.group(1), PromHistogram()).add(
                le, int(value))
            continue
        for outcome in OUTCOMES:
            if name == f"scaletorch_http_{outcome}":
                outcomes[outcome] = outcomes.get(outcome, 0) + int(value)
    return hists, outcomes


def collect(paths: List[str], prom_path: Optional[str]):
    """Fold every input into (samples, merged histograms, outcomes,
    prom histograms)."""
    samples: Dict[str, List[float]] = {m: [] for m in METRIC_FIELDS}
    merged: Dict[str, LogHistogram] = {}
    access_outcomes: Dict[str, int] = {}
    gw_metrics_last: Optional[dict] = None
    for path in paths:
        # latency_histograms records are CUMULATIVE snapshots of one
        # process's registry (the gateway re-emits its whole state on
        # the export cadence) — merging every record would multi-count
        # early observations, so only the LAST snapshot per process per
        # stream counts; merging happens across processes/streams.
        last_hists: Dict[Any, dict] = {}
        for event in read_jsonl(path):
            kind = event.get("kind")
            if kind == "access":
                outcome = event.get("outcome", "unknown")
                access_outcomes[outcome] = \
                    access_outcomes.get(outcome, 0) + 1
                served = outcome in LATENCY_OUTCOMES
                for metric, fname in METRIC_FIELDS.items():
                    # ttft mirrors the gateway histograms: observed at
                    # token arrival, so a present sample is real served
                    # latency whatever the eventual outcome (an aborted
                    # stream's first token still arrived). The terminal
                    # latencies (queue_wait/prefill/e2e) count for
                    # SERVED outcomes only — a refusal terminates in
                    # microseconds and would drag the quantiles DOWN
                    # under the exact overload the SLO exists to catch.
                    if metric != "ttft" and not served:
                        continue
                    value = event.get(fname)
                    if isinstance(value, (int, float)) \
                            and not isinstance(value, bool):
                        samples[metric].append(float(value))
            elif kind == "latency_histograms":
                last_hists[event.get("proc", 0)] = event
            elif kind == "gateway_metrics":
                gw_metrics_last = event
        for event in last_hists.values():
            for metric, series in event.items():
                if metric in ("v", "kind", "time", "proc") \
                        or not isinstance(series, dict):
                    continue
                for _label, obj in series.items():
                    if not isinstance(obj, dict) \
                            or "buckets" not in obj:
                        continue
                    h = LogHistogram.from_dict(obj)
                    if metric in merged:
                        merged[metric].merge(h)
                    else:
                        merged[metric] = h

    outcomes = access_outcomes
    if not outcomes and gw_metrics_last is not None:
        outcomes = {o: int(gw_metrics_last.get(f"http_{o}", 0))
                    for o in OUTCOMES}

    prom_hists: Dict[str, PromHistogram] = {}
    if prom_path is not None:
        with open(prom_path) as f:
            prom_hists, prom_outcomes = parse_prom_text(f.read())
        if not outcomes:
            outcomes = prom_outcomes
    return samples, merged, outcomes, prom_hists


def make_quantile_fn(samples, merged, prom_hists):
    """Exact samples win; merged JSONL histograms next; a /metrics
    scrape last."""

    def quantile(metric: str, q: float) -> Optional[float]:
        exact = samples.get(metric)
        if exact:
            ordered = sorted(exact)
            return ordered[min(len(ordered) - 1,
                               max(0, math.ceil(q * len(ordered)) - 1))]
        if metric in merged:
            return merged[metric].quantile(q)
        if metric in prom_hists:
            return prom_hists[metric].quantile(q)
        return None

    return quantile


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description=__doc__.split("\n")[0],
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("events", nargs="*",
                        help="telemetry JSONL file(s): access / "
                             "latency_histograms / gateway_metrics kinds")
    parser.add_argument("--slo", default=os.path.join(REPO, "tools",
                                                      "slo.json"),
                        help="SLO target file (default tools/slo.json)")
    parser.add_argument("--preset", required=True,
                        help="preset name inside the SLO file")
    parser.add_argument("--prom", default=None,
                        help="a scraped /metrics exposition to evaluate "
                             "(histogram _bucket series + http_* counters)")
    args = parser.parse_args(argv)

    if not args.events and args.prom is None:
        print("slo_check: provide at least one JSONL file or --prom",
              file=sys.stderr)
        return 2
    try:
        doc = load_slo(args.slo)
        spec = preset_targets(doc, args.preset)
        for path in list(args.events) + ([args.prom] if args.prom else []):
            if not os.path.exists(path):
                raise ValueError(f"input file not found: {path}")
        samples, merged, outcomes, prom_hists = collect(
            args.events, args.prom)
    except (OSError, ValueError, json.JSONDecodeError) as exc:
        print(f"slo_check: {exc}", file=sys.stderr)
        return 2

    result = evaluate_slo(
        spec, quantile_fn=make_quantile_fn(samples, merged, prom_hists),
        outcomes=outcomes)
    print(format_report(args.preset, result))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
