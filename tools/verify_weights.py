#!/usr/bin/env python
"""Verify HF checkpoint loading across model sizes.

Counterpart of reference tools/verify_qwen3.py: for each checkpoint dir,
load the weights, check parameter count / weight tying, run a forward
(finite logits) and a backward (finite loss, all grads present), and —
when transformers can load the same checkpoint on CPU — compare logits
token-for-token.

Usage:
    python tools/verify_weights.py /path/to/Qwen3-0.6B [/path/to/...]
    python tools/verify_weights.py --synthetic   # hermetic self-test
"""

from __future__ import annotations

import argparse
import os
import sys
import traceback

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def verify_one(path: str, compare_hf: bool = True) -> bool:
    import jax
    import jax.numpy as jnp
    from transformers import AutoConfig

    from scaletorch_tpu.models import llama, qwen3, qwen3_moe
    from scaletorch_tpu.utils.hf_interop import load_hf_params

    print(f"\n{'=' * 60}\nVerifying {path}\n{'=' * 60}")
    hf_cfg = AutoConfig.from_pretrained(path)
    mt = hf_cfg.model_type
    if mt == "qwen3_moe":
        cfg = qwen3_moe.Qwen3MoEConfig.from_hf(hf_cfg, dtype=jnp.float32)
        fwd = qwen3_moe.forward
    elif mt == "qwen3":
        cfg = qwen3.Qwen3Config.from_hf(hf_cfg, dtype=jnp.float32)
        fwd = llama.forward
    else:
        cfg = llama.LlamaConfig.from_hf(hf_cfg, dtype=jnp.float32)
        fwd = llama.forward

    params = load_hf_params(path, cfg)
    n = sum(x.size for x in jax.tree.leaves(params))
    print(f"  params: {n / 1e6:.1f}M (computed {cfg.num_params() / 1e6:.1f}M)")
    assert n == cfg.num_params(), "parameter count mismatch"
    if cfg.tie_word_embeddings:
        assert "lm_head" not in params
        print("  tie check: PASS (head reads the embedding)")

    ids = (np.arange(2 * 16, dtype=np.int32).reshape(2, 16) % cfg.vocab_size)
    out = fwd(params, ids, cfg)
    logits = out[0] if isinstance(out, tuple) else out
    assert np.all(np.isfinite(np.asarray(logits, dtype=np.float32)))
    print(f"  forward: PASS (shape={logits.shape}, finite)")

    def loss_fn(p):
        out = fwd(p, ids, cfg)
        lg = out[0] if isinstance(out, tuple) else out
        lp = jax.nn.log_softmax(lg.astype(jnp.float32))
        return -jnp.take_along_axis(
            lp[:, :-1], jnp.asarray(ids)[:, 1:, None], axis=-1
        ).mean()

    loss, grads = jax.value_and_grad(loss_fn)(params)
    n_grads = len(jax.tree.leaves(grads))
    assert np.isfinite(float(loss))
    print(f"  backward: PASS (loss={float(loss):.3f}, {n_grads} grad leaves)")

    if compare_hf:
        try:
            import torch
            from transformers import AutoModelForCausalLM

            model = AutoModelForCausalLM.from_pretrained(
                path, attn_implementation="eager",
                torch_dtype=torch.float32,
            ).eval()
            with torch.no_grad():
                theirs = model(torch.from_numpy(ids.astype(np.int64)))
            np.testing.assert_allclose(
                np.asarray(logits, np.float32),
                theirs.logits.float().numpy(),
                rtol=2e-3, atol=2e-3,
            )
            print("  logits vs transformers: PASS")
        except Exception as e:  # noqa: BLE001 — comparison is best-effort
            print(f"  logits vs transformers: SKIPPED ({repr(e)[:120]})")
    print("  RESULT: OK")
    return True


def synthetic_self_test() -> bool:
    """Round-trip our own saver -> verifier (hermetic)."""
    import tempfile

    import jax
    import jax.numpy as jnp

    from scaletorch_tpu.models import qwen3
    from scaletorch_tpu.models.llama import init_params
    from scaletorch_tpu.utils.hf_interop import save_hf_params

    cfg = qwen3.Qwen3Config(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, dtype=jnp.float32, tie_word_embeddings=False,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    with tempfile.TemporaryDirectory() as d:
        save_hf_params(d, params, cfg)
        # minimal HF config so AutoConfig resolves
        import json

        with open(os.path.join(d, "config.json"), "w") as f:
            json.dump({
                "model_type": "qwen3", "vocab_size": 128, "hidden_size": 32,
                "intermediate_size": 64, "num_hidden_layers": 2,
                "num_attention_heads": 4, "num_key_value_heads": 2,
                "head_dim": 16, "tie_word_embeddings": False,
                "rms_norm_eps": 1e-6, "rope_theta": 10000.0,
                "max_position_embeddings": 128,
                "architectures": ["Qwen3ForCausalLM"],
            }, f)
        return verify_one(d)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("paths", nargs="*", help="HF checkpoint dirs")
    ap.add_argument("--synthetic", action="store_true",
                    help="hermetic self-test via our own exporter")
    ap.add_argument("--no_hf_compare", action="store_true")
    args = ap.parse_args()

    targets = args.paths
    ok = True
    if args.synthetic or not targets:
        try:
            synthetic_self_test()
        except Exception:
            traceback.print_exc()
            ok = False
    for path in targets:
        try:
            verify_one(path, compare_hf=not args.no_hf_compare)
        except Exception:
            traceback.print_exc()
            ok = False
    print(f"\n{'=' * 60}\nAll verification complete: "
          f"{'OK' if ok else 'FAILURES'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
