#!/usr/bin/env python
"""Training entry point.

TPU-native counterpart of reference train.py:55-453: parse composed
dataclass args, set up the device mesh, build model/optimizer/data, run
the training loop with metrics + checkpointing.

Examples:
  # single chip, synthetic data
  python train.py --model_type llama --hidden_size 512 --num_hidden_layers 8 \
      --synthetic_data true --total_train_steps 20

  # 8 virtual CPU devices, DP8 (tests/multi-chip dry runs)
  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python train.py --data_parallel_size 8 --synthetic_data true ...
"""

from __future__ import annotations

import os
import sys


def main(argv=None) -> int:
    from scaletorch_tpu.config import parse_args
    from scaletorch_tpu.resilience import TrainingDivergedError
    from scaletorch_tpu.resilience_distributed import (
        DIVERGED_EXIT_CODE,
        WATCHDOG_EXIT_CODE,
        ElasticRemeshError,
    )
    from scaletorch_tpu.trainer.trainer import Trainer
    from scaletorch_tpu.utils.logger import get_logger

    cfg = parse_args(argv)
    trainer = Trainer(cfg)
    if trainer.telemetry.enabled:
        # the operator contract up front: where the artifacts land and
        # how to poke a live run (docs/observability.md)
        get_logger().info(
            f"telemetry enabled -> {trainer.telemetry.directory} "
            "(Chrome trace + JSONL event stream; kill -USR1 "
            f"{os.getpid()} dumps a live snapshot)"
        )
    # --resume auto: a restarted (e.g. preempted-and-rescheduled) job picks
    # up from the newest readable checkpoint and trains to the SAME
    # total_train_steps target; with no checkpoint yet it starts from
    # scratch. --resume must fails fast instead of silently restarting.
    if cfg.resume != "off" and cfg.checkpoint_dir:
        trainer.load_checkpoint(required=cfg.resume == "must")
    try:
        last = trainer.train()
        if trainer.preempted:
            # exit cleanly either way so the scheduler sees a graceful
            # shutdown, but be truthful about what survived
            if trainer.emergency_checkpoint_saved:
                get_logger().warning(
                    f"preempted at step {trainer.global_step}; emergency "
                    "checkpoint saved — restart with --resume auto to "
                    "continue"
                )
            else:
                get_logger().error(
                    f"preempted at step {trainer.global_step} and NO "
                    "emergency checkpoint could be written — a restart "
                    "resumes from the last periodic save (or scratch)"
                )
            return 0
        # final save BEFORE close() so the async dispatch is drained by
        # close()'s wait — otherwise the process could exit mid-write
        if cfg.checkpoint_dir and cfg.save_frequency:
            trainer.save_checkpoint()
    except TrainingDivergedError as exc:
        # the trainer already wrote results/crash_report_step<N>.json;
        # exit with the documented code so launchers/schedulers can tell
        # "diverged, needs a human" from "preempted, just restart"
        # (docs/fault_tolerance.md exit-code contract; the hang watchdog
        # exits 43 directly from its monitor thread)
        get_logger().error(f"training aborted: {exc}")
        return DIVERGED_EXIT_CODE
    except ElasticRemeshError as exc:
        # the elastic coordinator could not continue (un-shrinkable
        # geometry, min-hosts floor, membership store unreachable):
        # restart-family exit — the launcher's fleet-wide restart is the
        # fallback, never a human (42 stays reserved for divergence)
        get_logger().error(f"elastic continuation impossible: {exc}")
        return WATCHDOG_EXIT_CODE
    except KeyboardInterrupt:
        get_logger().warning("interrupted; exiting")
        return 130
    finally:
        # drain in-flight async checkpoint saves + finish wandb even on
        # interrupt/error (reference aborts with cleanup, train.py:257-268)
        trainer.close()
    get_logger().info(f"done: {last}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
